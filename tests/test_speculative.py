"""Speculative decoding (models/speculative.py): the greedy variant's
defining property is EXACT token equality with plain greedy decoding
of the target model — speculation may only change how many target
passes it takes, never the output."""

import dataclasses

import numpy as np
import pytest

import jax

from parameter_server_tpu.models.speculative import speculative_generate
from parameter_server_tpu.models.transformer import (
    LMConfig,
    init_lm,
    lm_generate,
)

# Promoted to the slow tier (PR 2, per the PR-1 ROADMAP note): the
# shard_map-shim unlock made the full 'not slow' suite overrun the
# 870s tier-1 budget on a 2-core host. Run via `pytest -m slow`.
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def tcfg():
    return LMConfig(vocab=32, d_model=32, n_heads=2, n_layers=2, d_ff=64)


@pytest.fixture(scope="module")
def dcfg():
    # a genuinely smaller draft: narrower and shallower
    return LMConfig(vocab=32, d_model=16, n_heads=2, n_layers=1, d_ff=32)


@pytest.fixture(scope="module")
def tparams(tcfg):
    return init_lm(jax.random.PRNGKey(0), tcfg)


@pytest.fixture(scope="module")
def dparams(dcfg):
    return init_lm(jax.random.PRNGKey(1), dcfg)


def _prompt(b=2, p=9, seed=3):
    return np.random.default_rng(seed).integers(0, 32, (b, p)).astype(
        np.int32
    )


class TestExactness:
    @pytest.mark.parametrize("gamma", [1, 3, 4])
    def test_matches_plain_greedy(self, tcfg, dcfg, tparams, dparams, gamma):
        prompt = _prompt()
        want = np.asarray(lm_generate(tparams, prompt, tcfg, steps=14))
        got = np.asarray(
            speculative_generate(
                tparams, tcfg, dparams, dcfg, prompt, steps=14, gamma=gamma
            )
        )
        np.testing.assert_array_equal(got, want)

    def test_matches_under_feature_composition(self):
        """Target with GQA+rope+bf16, draft with rope — each model runs
        its own config; output still exactly equals plain greedy."""
        tcfg = LMConfig(
            vocab=32, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            n_kv_heads=2, rope=True, compute_dtype="bfloat16",
        )
        dcfg = LMConfig(
            vocab=32, d_model=16, n_heads=2, n_layers=1, d_ff=32, rope=True
        )
        tp = init_lm(jax.random.PRNGKey(4), tcfg)
        dp = init_lm(jax.random.PRNGKey(5), dcfg)
        prompt = _prompt(seed=6)
        want = np.asarray(lm_generate(tp, prompt, tcfg, steps=10))
        got = np.asarray(
            speculative_generate(tp, tcfg, dp, dcfg, prompt, steps=10,
                                 gamma=3)
        )
        np.testing.assert_array_equal(got, want)

    def test_matches_with_int8_caches(self, tcfg, dcfg, tparams, dparams):
        """int8 KV caches on BOTH models (the per-row scale-scatter
        write path is only reachable here): output equals plain greedy
        decode of the target with the SAME int8 cache config."""
        t8 = dataclasses.replace(tcfg, kv_cache_dtype="int8")
        d8 = dataclasses.replace(dcfg, kv_cache_dtype="int8")
        prompt = _prompt(seed=12)
        want = np.asarray(lm_generate(tparams, prompt, t8, steps=10))
        got = np.asarray(
            speculative_generate(
                tparams, t8, dparams, d8, prompt, steps=10, gamma=3
            )
        )
        np.testing.assert_array_equal(got, want)

    def test_single_step_and_odd_lengths(self, tcfg, dcfg, tparams, dparams):
        """steps smaller than gamma, and steps=1, must still terminate
        and match (the capped-commit path)."""
        prompt = _prompt(b=3, p=5, seed=7)
        for steps in (1, 2):
            want = np.asarray(lm_generate(tparams, prompt, tcfg, steps=steps))
            got = np.asarray(
                speculative_generate(
                    tparams, tcfg, dparams, dcfg, prompt, steps=steps,
                    gamma=4,
                )
            )
            np.testing.assert_array_equal(got, want)


class TestSpeedupMechanics:
    def test_perfect_draft_accepts_everything(self, tcfg, tparams):
        """draft == target: every proposal is accepted, so steps tokens
        arrive in ~steps/(gamma+1) rounds — the upper bound on what a
        draft can buy."""
        prompt = _prompt(seed=8)
        steps, gamma = 16, 3
        out, stats = speculative_generate(
            tparams, tcfg, tparams, tcfg, prompt, steps=steps, gamma=gamma,
            return_stats=True,
        )
        want = np.asarray(lm_generate(tparams, prompt, tcfg, steps=steps))
        np.testing.assert_array_equal(np.asarray(out), want)
        assert float(stats["accepted_frac"]) > 0.99, stats
        # ceil(steps / (gamma+1)) rounds when everything is accepted
        assert int(stats["rounds"]) <= -(-steps // (gamma + 1)) + 1, stats

    def test_stats_reported_for_weak_draft(self, tcfg, dcfg, tparams,
                                           dparams):
        out, stats = speculative_generate(
            tparams, tcfg, dparams, dcfg, _prompt(seed=9), steps=12,
            gamma=4, return_stats=True,
        )
        assert int(stats["rounds"]) >= 1
        assert 0.0 <= float(stats["accepted_frac"]) <= 1.0
        assert int(stats["target_passes"]) == int(stats["rounds"])
        # a random draft against a random target still cannot take MORE
        # rounds than one commit per round
        assert int(stats["rounds"]) <= 12


class TestRejectionPath:
    def test_full_rejection_still_exact(self):
        """Random-init models collapse to near-constant emissions, so
        acceptance is usually all-or-nothing; this seed pair REJECTS
        every proposal (verified when the test was written) — one
        committed token per round, pure correction path — and the
        output still exactly equals plain greedy."""
        tcfg = LMConfig(vocab=32, d_model=32, n_heads=2, n_layers=2, d_ff=64)
        dcfg = LMConfig(vocab=32, d_model=16, n_heads=2, n_layers=1, d_ff=32)
        tp = init_lm(jax.random.PRNGKey(4), tcfg)
        dp = init_lm(jax.random.PRNGKey(104), dcfg)
        prompt = _prompt(b=2, p=8, seed=4)  # this exact prompt rejects
        want = np.asarray(lm_generate(tp, prompt, tcfg, steps=16))
        got, st = speculative_generate(
            tp, tcfg, dp, dcfg, prompt, steps=16, gamma=4,
            return_stats=True,
        )
        np.testing.assert_array_equal(np.asarray(got), want)
        # seed-dependent numerics: assert the INTENT (mostly-rejecting)
        # with slack for a stray tie flip on another backend, not the
        # exact round count
        assert float(st["accepted_frac"]) < 0.5, st
        assert 8 <= int(st["rounds"]) <= 15, st


class TestSampledVariant:
    def test_acceptance_core_preserves_target_distribution(self):
        """Leviathan Thm 1, pinned statistically on the pure core: for
        ANY draft distribution, the emitted token's marginal is exactly
        the target's. Vocab 8, fixed p_d far from p_t, 40k vmapped
        keys; TV distance of the position-0 emission < 2%."""
        import jax
        import jax.numpy as jnp

        from parameter_server_tpu.models.speculative import (
            _accept_and_correct,
        )

        v = 8
        rng = np.random.default_rng(0)
        p_t = rng.dirichlet(np.ones(v))
        p_d = rng.dirichlet(np.ones(v) * 0.3)  # deliberately mismatched
        p_d_b = jnp.asarray(p_d, jnp.float32)[None, None, :]  # [1,1,V]
        p_t_b = jnp.tile(
            jnp.asarray(p_t, jnp.float32)[None, None, :], (1, 2, 1)
        )  # [1, 2, V] (position 0 + bonus)

        n_keys = 40_000
        keys = jax.random.split(jax.random.PRNGKey(1), n_keys)

        def one(key):
            kd, ka = jax.random.split(key)
            d = jax.random.categorical(
                kd, jnp.log(p_d_b[:, 0]), axis=-1
            ).astype(jnp.int32)[:, None]  # [1,1] sampled FROM p_d
            _, commit = _accept_and_correct(ka, d, p_d_b, p_t_b)
            return commit[0, 0]  # the position-0 emission

        toks = np.asarray(jax.vmap(one)(keys))
        emp = np.bincount(toks, minlength=v) / n_keys
        tv = 0.5 * np.abs(emp - p_t).sum()
        assert tv < 0.02, (tv, emp, p_t)

    def test_identical_models_accept_everything(self):
        """p_d == p_t: acceptance probability is 1 — no rejection ever."""
        import jax
        import jax.numpy as jnp

        from parameter_server_tpu.models.speculative import (
            _accept_and_correct,
        )

        p = jnp.asarray(
            np.random.default_rng(2).dirichlet(np.ones(8), size=(4, 3)),
            jnp.float32,
        )  # [B=4, g=3, V]
        p_t = jnp.concatenate([p, p[:, :1]], axis=1)  # [B, 4, V]
        d = jnp.zeros((4, 3), jnp.int32)  # any proposals
        n, _ = _accept_and_correct(jax.random.PRNGKey(3), d, p, p_t)
        assert (np.asarray(n) == 3).all(), n

    def test_sampled_end_to_end_runs_and_is_reproducible(
        self, tcfg, dcfg, tparams, dparams
    ):
        """The sampled path through the full models: valid tokens, same
        key -> same output, different key -> (almost surely) different."""
        import jax

        prompt = _prompt(seed=10)
        out1, st = speculative_generate(
            tparams, tcfg, dparams, dcfg, prompt, steps=12, gamma=3,
            temperature=1.0, key=jax.random.PRNGKey(0), return_stats=True,
        )
        out2 = speculative_generate(
            tparams, tcfg, dparams, dcfg, prompt, steps=12, gamma=3,
            temperature=1.0, key=jax.random.PRNGKey(0),
        )
        np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
        assert ((0 <= np.asarray(out1)) & (np.asarray(out1) < 32)).all()
        assert int(st["rounds"]) >= 1
        out3 = speculative_generate(
            tparams, tcfg, dparams, dcfg, prompt, steps=12, gamma=3,
            temperature=1.0, key=jax.random.PRNGKey(9),
        )
        assert not np.array_equal(np.asarray(out1), np.asarray(out3))

    def test_array_temperature_does_not_recompile_or_crash(
        self, tcfg, dcfg, tparams, dparams
    ):
        """A traced/Array temperature is sampling (same contract as
        lm_generate): sweeping it must neither crash on the static
        greedy flag nor recompile."""
        import jax
        import jax.numpy as jnp

        prompt = _prompt(seed=13)
        for t in (jnp.float32(0.7), jnp.float32(1.3)):
            out = speculative_generate(
                tparams, tcfg, dparams, dcfg, prompt, steps=6, gamma=2,
                temperature=t, key=jax.random.PRNGKey(0),
            )
            assert np.asarray(out).shape == (2, 15)

    def test_sampling_needs_key(self, tcfg, dcfg, tparams, dparams):
        with pytest.raises(ValueError, match="PRNG key"):
            speculative_generate(
                tparams, tcfg, dparams, dcfg, _prompt(), steps=4,
                temperature=1.0,
            )


class TestValidation:
    def test_rejects_vocab_mismatch(self, tcfg, tparams):
        bad = LMConfig(vocab=64, d_model=16, n_heads=2, n_layers=1, d_ff=32)
        with pytest.raises(ValueError, match="vocab"):
            speculative_generate(
                tparams, tcfg, init_lm(jax.random.PRNGKey(2), bad), bad,
                _prompt(), steps=4,
            )

    def test_rejects_bad_gamma(self, tcfg, dcfg, tparams, dparams):
        # (MoE targets are SUPPORTED since round 4 — see
        # tests/test_moe_serving.py::test_moe_speculative_target)
        with pytest.raises(ValueError, match="gamma"):
            speculative_generate(
                tparams, tcfg, dparams, dcfg, _prompt(), steps=4, gamma=0
            )
