"""Speculative decoding (models/speculative.py): the greedy variant's
defining property is EXACT token equality with plain greedy decoding
of the target model — speculation may only change how many target
passes it takes, never the output."""

import dataclasses

import numpy as np
import pytest

import jax

from parameter_server_tpu.models.speculative import speculative_generate
from parameter_server_tpu.models.transformer import (
    LMConfig,
    init_lm,
    lm_generate,
)


@pytest.fixture(scope="module")
def tcfg():
    return LMConfig(vocab=32, d_model=32, n_heads=2, n_layers=2, d_ff=64)


@pytest.fixture(scope="module")
def dcfg():
    # a genuinely smaller draft: narrower and shallower
    return LMConfig(vocab=32, d_model=16, n_heads=2, n_layers=1, d_ff=32)


@pytest.fixture(scope="module")
def tparams(tcfg):
    return init_lm(jax.random.PRNGKey(0), tcfg)


@pytest.fixture(scope="module")
def dparams(dcfg):
    return init_lm(jax.random.PRNGKey(1), dcfg)


def _prompt(b=2, p=9, seed=3):
    return np.random.default_rng(seed).integers(0, 32, (b, p)).astype(
        np.int32
    )


class TestExactness:
    @pytest.mark.parametrize("gamma", [1, 3, 4])
    def test_matches_plain_greedy(self, tcfg, dcfg, tparams, dparams, gamma):
        prompt = _prompt()
        want = np.asarray(lm_generate(tparams, prompt, tcfg, steps=14))
        got = np.asarray(
            speculative_generate(
                tparams, tcfg, dparams, dcfg, prompt, steps=14, gamma=gamma
            )
        )
        np.testing.assert_array_equal(got, want)

    def test_matches_under_feature_composition(self):
        """Target with GQA+rope+bf16, draft with rope — each model runs
        its own config; output still exactly equals plain greedy."""
        tcfg = LMConfig(
            vocab=32, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            n_kv_heads=2, rope=True, compute_dtype="bfloat16",
        )
        dcfg = LMConfig(
            vocab=32, d_model=16, n_heads=2, n_layers=1, d_ff=32, rope=True
        )
        tp = init_lm(jax.random.PRNGKey(4), tcfg)
        dp = init_lm(jax.random.PRNGKey(5), dcfg)
        prompt = _prompt(seed=6)
        want = np.asarray(lm_generate(tp, prompt, tcfg, steps=10))
        got = np.asarray(
            speculative_generate(tp, tcfg, dp, dcfg, prompt, steps=10,
                                 gamma=3)
        )
        np.testing.assert_array_equal(got, want)

    def test_matches_with_int8_caches(self, tcfg, dcfg, tparams, dparams):
        """int8 KV caches on BOTH models (the per-row scale-scatter
        write path is only reachable here): output equals plain greedy
        decode of the target with the SAME int8 cache config."""
        t8 = dataclasses.replace(tcfg, kv_cache_dtype="int8")
        d8 = dataclasses.replace(dcfg, kv_cache_dtype="int8")
        prompt = _prompt(seed=12)
        want = np.asarray(lm_generate(tparams, prompt, t8, steps=10))
        got = np.asarray(
            speculative_generate(
                tparams, t8, dparams, d8, prompt, steps=10, gamma=3
            )
        )
        np.testing.assert_array_equal(got, want)

    def test_single_step_and_odd_lengths(self, tcfg, dcfg, tparams, dparams):
        """steps smaller than gamma, and steps=1, must still terminate
        and match (the capped-commit path)."""
        prompt = _prompt(b=3, p=5, seed=7)
        for steps in (1, 2):
            want = np.asarray(lm_generate(tparams, prompt, tcfg, steps=steps))
            got = np.asarray(
                speculative_generate(
                    tparams, tcfg, dparams, dcfg, prompt, steps=steps,
                    gamma=4,
                )
            )
            np.testing.assert_array_equal(got, want)


class TestSpeedupMechanics:
    def test_perfect_draft_accepts_everything(self, tcfg, tparams):
        """draft == target: every proposal is accepted, so steps tokens
        arrive in ~steps/(gamma+1) rounds — the upper bound on what a
        draft can buy."""
        prompt = _prompt(seed=8)
        steps, gamma = 16, 3
        out, stats = speculative_generate(
            tparams, tcfg, tparams, tcfg, prompt, steps=steps, gamma=gamma,
            return_stats=True,
        )
        want = np.asarray(lm_generate(tparams, prompt, tcfg, steps=steps))
        np.testing.assert_array_equal(np.asarray(out), want)
        assert float(stats["accepted_frac"]) > 0.99, stats
        # ceil(steps / (gamma+1)) rounds when everything is accepted
        assert int(stats["rounds"]) <= -(-steps // (gamma + 1)) + 1, stats

    def test_stats_reported_for_weak_draft(self, tcfg, dcfg, tparams,
                                           dparams):
        out, stats = speculative_generate(
            tparams, tcfg, dparams, dcfg, _prompt(seed=9), steps=12,
            gamma=4, return_stats=True,
        )
        assert int(stats["rounds"]) >= 1
        assert 0.0 <= float(stats["accepted_frac"]) <= 1.0
        assert int(stats["target_passes"]) == int(stats["rounds"])
        # a random draft against a random target still cannot take MORE
        # rounds than one commit per round
        assert int(stats["rounds"]) <= 12


class TestRejectionPath:
    def test_full_rejection_still_exact(self):
        """Random-init models collapse to near-constant emissions, so
        acceptance is usually all-or-nothing; this seed pair REJECTS
        every proposal (verified when the test was written) — one
        committed token per round, pure correction path — and the
        output still exactly equals plain greedy."""
        tcfg = LMConfig(vocab=32, d_model=32, n_heads=2, n_layers=2, d_ff=64)
        dcfg = LMConfig(vocab=32, d_model=16, n_heads=2, n_layers=1, d_ff=32)
        tp = init_lm(jax.random.PRNGKey(4), tcfg)
        dp = init_lm(jax.random.PRNGKey(104), dcfg)
        prompt = _prompt(b=2, p=8, seed=4)  # this exact prompt rejects
        want = np.asarray(lm_generate(tp, prompt, tcfg, steps=16))
        got, st = speculative_generate(
            tp, tcfg, dp, dcfg, prompt, steps=16, gamma=4,
            return_stats=True,
        )
        np.testing.assert_array_equal(np.asarray(got), want)
        # seed-dependent numerics: assert the INTENT (mostly-rejecting)
        # with slack for a stray tie flip on another backend, not the
        # exact round count
        assert float(st["accepted_frac"]) < 0.5, st
        assert 8 <= int(st["rounds"]) <= 15, st


class TestValidation:
    def test_rejects_vocab_mismatch(self, tcfg, tparams):
        bad = LMConfig(vocab=64, d_model=16, n_heads=2, n_layers=1, d_ff=32)
        with pytest.raises(ValueError, match="vocab"):
            speculative_generate(
                tparams, tcfg, init_lm(jax.random.PRNGKey(2), bad), bad,
                _prompt(), steps=4,
            )

    def test_rejects_moe_and_bad_gamma(self, tcfg, dcfg, tparams, dparams):
        moe = dataclasses.replace(tcfg, moe_every=2)
        with pytest.raises(ValueError, match="dense-FFN"):
            speculative_generate(
                tparams, moe, dparams, dcfg, _prompt(), steps=4
            )
        with pytest.raises(ValueError, match="gamma"):
            speculative_generate(
                tparams, tcfg, dparams, dcfg, _prompt(), steps=4, gamma=0
            )
