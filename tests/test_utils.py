"""Unit tests for utils — mirrors reference gtest coverage in src/test/
(common_test, bloom_filter_test, countmin_test, localizer_test,
parallel_ordered_match_test, sparse_matrix_test, assign_op_test)."""

import io

import numpy as np
import pytest

from parameter_server_tpu.utils import crc32c, evaluation, recordio
from parameter_server_tpu.utils.assign_op import AssignOp, apply_op
from parameter_server_tpu.utils.bitmap import Bitmap
from parameter_server_tpu.utils.localizer import Localizer, count_uniq_keys, remap
from parameter_server_tpu.utils.ordered_match import ordered_match
from parameter_server_tpu.utils.range import Range
from parameter_server_tpu.utils.sketch import BloomFilter, CountMin
from parameter_server_tpu.utils.sparse import SparseBatch, from_dense, random_sparse


class TestRange:
    def test_even_divide(self):
        r = Range(0, 10)
        parts = r.divide(3)
        assert parts[0] == Range(0, 3)
        assert parts[1] == Range(3, 6)
        assert parts[2] == Range(6, 10)
        assert sum(p.size() for p in parts) == 10

    def test_intersection(self):
        assert Range(0, 5).intersection(Range(3, 9)) == Range(3, 5)
        assert Range(0, 2).intersection(Range(3, 9)).empty()

    def test_contains(self):
        assert 3 in Range(0, 5)
        assert 5 not in Range(0, 5)


class TestSparse:
    def test_from_dense_roundtrip(self, rng):
        x = (rng.random((7, 11)) < 0.3) * rng.normal(size=(7, 11))
        y = np.sign(rng.normal(size=7))
        b = from_dense(x.astype(np.float32), y)
        np.testing.assert_allclose(b.to_dense(), x, rtol=1e-6)

    def test_csc_matches_dense(self, rng):
        b = random_sparse(50, 31, 4, seed=1)
        dense = b.to_dense()
        csc = b.to_csc()
        for j in range(b.cols):
            rows, vals = csc.col(j)
            col = np.zeros(b.n, dtype=np.float32)
            if vals is None:
                col[rows] = 1.0
            else:
                np.add.at(col, rows, vals)
            np.testing.assert_allclose(col, dense[:, j], rtol=1e-5)

    def test_pad_device(self):
        b = random_sparse(10, 20, 3, seed=2)
        pb = b.pad_device(nnz_pad=64, rows_pad=16)
        assert pb.rows_pad == 16 and pb.nnz_pad == 64
        assert pb.row_mask.sum() == 10
        # padded entries point at sentinel col with zero value
        assert (pb.cols[b.nnz :] == b.cols).all()
        assert (pb.vals[b.nnz :] == 0).all()
        # matvec through padding equals dense matvec
        w = np.random.default_rng(0).normal(size=b.cols + 1).astype(np.float32)
        w[-1] = 123.0  # sentinel weight must not matter (value=0)
        xw_pad = np.zeros(16, dtype=np.float32)
        np.add.at(xw_pad, pb.rows, pb.vals * w[pb.cols])
        np.testing.assert_allclose(xw_pad[:10], b.to_dense() @ w[:-1], rtol=1e-4)

    def test_slice_rows(self):
        b = random_sparse(10, 20, 3, seed=3)
        s = b.slice_rows(2, 5)
        np.testing.assert_allclose(s.to_dense(), b.to_dense()[2:5], rtol=1e-6)


class TestLocalizer:
    def test_count_uniq(self):
        b = SparseBatch(
            y=np.ones(2, np.float32),
            indptr=np.array([0, 3, 5]),
            indices=np.array([9, 4, 9, 4, 1]),
            values=np.arange(5, dtype=np.float32),
        )
        keys, cnt = count_uniq_keys(b)
        np.testing.assert_array_equal(keys, [1, 4, 9])
        np.testing.assert_array_equal(cnt, [1, 2, 2])

    def test_remap_keeps_subset(self):
        b = SparseBatch(
            y=np.ones(2, np.float32),
            indptr=np.array([0, 3, 5]),
            indices=np.array([9, 4, 9, 4, 1]),
            values=np.arange(5, dtype=np.float32),
        )
        out = remap(b, np.array([4, 9]))
        assert out.cols == 2
        np.testing.assert_array_equal(out.indices, [1, 0, 1, 0])  # key9->1, key4->0
        np.testing.assert_array_equal(out.indptr, [0, 3, 4])
        np.testing.assert_array_equal(out.values, [0, 1, 2, 3])  # key1 dropped

    def test_localizer_protocol(self):
        b = random_sparse(20, 50, 5, seed=4)
        loc = Localizer()
        keys, cnt = loc.count_uniq_index(b)
        out = loc.remap_index(keys)
        # full keep: dense reconstruction must match with remapped columns
        np.testing.assert_allclose(
            out.to_dense(), b.to_dense()[:, keys.astype(int)], rtol=1e-6
        )


class TestOrderedMatch:
    def test_assign_and_plus(self):
        dst_k = np.array([1, 3, 5, 7])
        dst_v = np.zeros(4, dtype=np.float32)
        src_k = np.array([3, 5, 9])
        src_v = np.array([30.0, 50.0, 90.0], dtype=np.float32)
        n = ordered_match(dst_k, dst_v, src_k, src_v)
        assert n == 2
        np.testing.assert_array_equal(dst_v, [0, 30, 50, 0])
        n = ordered_match(dst_k, dst_v, src_k, src_v, op=AssignOp.PLUS)
        np.testing.assert_array_equal(dst_v, [0, 60, 100, 0])

    def test_width_k(self):
        dst_k = np.array([2, 4])
        dst_v = np.zeros((2, 3), dtype=np.float32)
        src_k = np.array([4])
        src_v = np.array([[1.0, 2.0, 3.0]], dtype=np.float32)
        ordered_match(dst_k, dst_v, src_k, src_v, k=3)
        np.testing.assert_array_equal(dst_v[1], [1, 2, 3])


class TestSketches:
    def test_bloom_no_false_negatives(self, rng):
        bf = BloomFilter(1 << 16, 3)
        keys = rng.integers(0, 1 << 60, size=1000).astype(np.uint64)
        bf.insert(keys)
        assert bf.query(keys).all()

    def test_bloom_low_false_positive(self, rng):
        bf = BloomFilter(1 << 18, 3)
        keys = rng.integers(0, 1 << 60, size=1000).astype(np.uint64)
        bf.insert(keys)
        other = rng.integers(1 << 61, 1 << 62, size=10000).astype(np.uint64)
        assert bf.query(other).mean() < 0.01

    def test_countmin_upper_bound(self, rng):
        cm = CountMin(1 << 16, 3)
        keys = rng.integers(0, 1 << 40, size=500).astype(np.uint64)
        cm.insert(keys, 5)
        est = cm.query(keys)
        assert (est >= 5).all()  # never underestimates
        fresh = rng.integers(1 << 41, 1 << 42, size=500).astype(np.uint64)
        assert cm.query(fresh).mean() < 1.0


class TestEvaluation:
    def test_auc_perfect_and_random(self):
        y = np.array([1, 1, -1, -1], dtype=np.float32)
        assert evaluation.auc(y, np.array([2.0, 1.5, -1.0, -2.0])) == 1.0
        assert evaluation.auc(y, np.array([-2.0, -1.5, 1.0, 2.0])) == 0.0
        assert abs(evaluation.auc(y, np.zeros(4)) - 0.5) < 1e-9

    def test_accuracy(self):
        y = np.array([1, -1, 1, -1], dtype=np.float32)
        assert evaluation.accuracy(y, np.array([1.0, -1.0, -1.0, 1.0])) == 0.5

    def test_logloss(self):
        y = np.array([1.0, -1.0])
        xw = np.array([100.0, -100.0])
        assert evaluation.logloss(y, xw) < 1e-6


class TestCrcRecordio:
    def test_crc_known_value(self):
        # crc32c("123456789") = 0xE3069283 (Castagnoli standard test vector)
        assert crc32c.value(b"123456789") == 0xE3069283

    def test_mask_roundtrip(self):
        c = crc32c.value(b"hello")
        assert crc32c.unmask(crc32c.masked(c)) == c

    def test_recordio_roundtrip(self):
        buf = io.BytesIO()
        w = recordio.RecordWriter(buf)
        recs = [b"alpha", b"", b"x" * 1000]
        for r in recs:
            w.write_record(r)
        buf.seek(0)
        assert list(recordio.RecordReader(buf)) == recs

    def test_recordio_detects_corruption(self):
        buf = io.BytesIO()
        recordio.RecordWriter(buf).write_record(b"payload")
        data = bytearray(buf.getvalue())
        data[-1] ^= 0xFF
        with pytest.raises(IOError):
            recordio.RecordReader(io.BytesIO(bytes(data))).read_record()


class TestBitmapAssign:
    def test_bitmap(self):
        bm = Bitmap(10, True)
        assert bm.nnz() == 10
        bm.clear(3)
        assert not bm.test(3) and bm.nnz() == 9
        bm.fill(False)
        assert bm.nnz() == 0

    def test_assign_ops(self):
        assert apply_op(AssignOp.PLUS, 2.0, 3.0) == 5.0
        assert apply_op(AssignOp.ASSIGN, 2.0, 3.0) == 3.0
        assert apply_op(AssignOp.TIMES, 2.0, 3.0) == 6.0


def test_hash_slots_batchsize_invariant():
    """C++ fused path (large batches) and NumPy fallback (small) must map
    identical keys to identical slots — slot assignment can never depend on
    batch size or native-library availability."""
    from parameter_server_tpu.utils.murmur import hash_slots

    keys = np.random.default_rng(3).integers(0, 1 << 62, size=8192).astype(np.int64)
    big = hash_slots(keys, 1 << 20)
    small = np.concatenate([hash_slots(keys[i : i + 64], 1 << 20) for i in range(0, 8192, 64)])
    np.testing.assert_array_equal(big, small)
    assert big.dtype == np.int32 and big.min() >= 0 and big.max() < (1 << 20)
    # non-pow2 table size exercises the modulo path
    np.testing.assert_array_equal(
        hash_slots(keys, 1_000_003),
        np.concatenate([hash_slots(keys[:4096], 1_000_003), hash_slots(keys[4096:], 1_000_003)]),
    )


class TestBitpack:
    """utils/bitpack: bitstream wire format (pack host-side, unpack in jit)."""

    def test_cpp_matches_numpy(self, rng):
        from parameter_server_tpu.utils import bitpack

        for bits in (7, 22, 23, 24):
            vals = rng.integers(0, 1 << bits, 9000).astype(np.int32)
            np.testing.assert_array_equal(
                bitpack.pack_bits(vals, bits), bitpack.pack_bits_np(vals, bits)
            )

    def test_fused_hash_pack_matches_two_pass(self, rng):
        from parameter_server_tpu.utils import bitpack
        from parameter_server_tpu.utils.murmur import hash_slots

        keys = rng.integers(0, 1 << 62, 50000).astype(np.uint64)
        num_slots = 1 << 18
        want = bitpack.pack_bits_np(hash_slots(keys, num_slots), 18)
        np.testing.assert_array_equal(
            bitpack.hash_slots_packed(keys, num_slots, 18), want
        )

    def test_device_unpack_roundtrip(self, rng):
        import jax

        from parameter_server_tpu.utils import bitpack

        for bits in (13, 22):
            vals = rng.integers(0, 1 << bits, 4096 * 3 + 5).astype(np.int32)
            words = bitpack.stream_to_words(
                bitpack.pack_bits(vals, bits), vals.size, bits
            )
            out = jax.jit(
                lambda w, n=vals.size, b=bits: bitpack.unpack_bits(w, n, b)
            )(words)
            np.testing.assert_array_equal(np.asarray(out), vals)

    def test_tiled_unpack_matches_gather_all_widths(self, rng):
        """The gather-free tiled unpack (the production decode path:
        rows_pad*lanes is always period-aligned) must be bit-exact with
        the general two-gather form at EVERY wire width, including the
        carry lanes that straddle word boundaries."""
        import jax

        from parameter_server_tpu.utils import bitpack

        for bits in range(1, 32):
            v_per, _ = bitpack._bit_period(bits)
            for nper in (1, 7):
                n = v_per * nper
                vals = rng.integers(0, 1 << bits, n, endpoint=False)
                vals = vals.astype(np.int64).astype(np.int32)
                words = bitpack.stream_to_words(
                    bitpack.pack_bits_np(vals, bits), n, bits
                )
                tiled = jax.jit(
                    lambda w, n=n, b=bits: bitpack._unpack_bits_tiled(
                        w, n, b
                    )
                )(words)
                gath = jax.jit(
                    lambda w, n=n, b=bits: bitpack._unpack_bits_gather(
                        w, n, b
                    )
                )(words)
                np.testing.assert_array_equal(np.asarray(tiled), vals)
                np.testing.assert_array_equal(
                    np.asarray(tiled), np.asarray(gath)
                )

    def test_sign_bits_roundtrip(self, rng):
        import jax

        from parameter_server_tpu.utils import bitpack

        y = np.where(rng.random(1000) > 0.5, 1.0, -1.0).astype(np.float32)
        packed = np.packbits(y > 0, bitorder="little")
        out = jax.jit(lambda b: bitpack.unpack_sign_bits(b, y.size))(packed)
        np.testing.assert_array_equal(np.asarray(out), y)


class TestMurmur3:
    """Real MurmurHash3 x64 128 (ref util/murmurhash3.cc; criteo keys)."""

    def test_python_matches_cpp(self):
        import parameter_server_tpu.cpp as cpp
        from parameter_server_tpu.utils.murmur import murmur3_x64_128

        if cpp.native() is None:
            return
        tests = [b"", b"a", b"hello", b"0a1b2c3d", b"x" * 15, b"y" * 16, b"z" * 33]
        want = [murmur3_x64_128(t, 512927377) for t in tests]
        real = cpp.native
        cpp.native = lambda: None
        try:
            got = [murmur3_x64_128(t, 512927377) for t in tests]
        finally:
            cpp.native = real
        assert want == got

    def test_deterministic_and_seeded(self):
        from parameter_server_tpu.utils.murmur import murmur3_x64_128

        a = murmur3_x64_128(b"token", 512927377)
        assert a == murmur3_x64_128(b"token", 512927377)
        assert a != murmur3_x64_128(b"token", 1)
        assert a != murmur3_x64_128(b"tokeN", 512927377)


class TestDeviceLock:
    """Advisory device flock (utils/device_lock.py): exclusivity with
    bounded-wait fallback, and the holder-child no-op contract that
    keeps onchip.py's task children from deadlocking on their parent."""

    def test_exclusive_then_timeout_proceeds(self, tmp_path, monkeypatch):
        import os
        import subprocess
        import sys

        from parameter_server_tpu.utils.device_lock import device_lock

        lock = str(tmp_path / "dev.lock")
        monkeypatch.setenv("PS_DEVICE_LOCK", lock)
        # hermetic even when pytest itself runs under a lock holder
        monkeypatch.delenv("PS_DEVICE_LOCK_HELD", raising=False)
        child_env = {
            k: v for k, v in os.environ.items()
            if k != "PS_DEVICE_LOCK_HELD"
        }
        child = (
            "import os, sys; sys.path.insert(0, %r); "
            "os.environ['PS_DEVICE_LOCK'] = %r; "
            "from parameter_server_tpu.utils.device_lock import device_lock; "
            "ok = None\n"
            "with device_lock(timeout_s=0.1, poll_s=0.05) as got: ok = got\n"
            "sys.exit(0 if not ok else 3)"
        ) % (str(__import__('pathlib').Path(__file__).parents[1]), lock)
        with device_lock() as got:
            assert got
            r = subprocess.run(
                [sys.executable, "-c", child], timeout=60, env=child_env
            )
            # contender times out, reports not-acquired, still proceeds
            assert r.returncode == 0
        with device_lock(timeout_s=0) as got2:  # free again after release
            assert got2

    def test_held_env_skips_acquisition(self, tmp_path, monkeypatch):
        from parameter_server_tpu.utils.device_lock import device_lock

        monkeypatch.setenv("PS_DEVICE_LOCK", str(tmp_path / "dev.lock"))
        monkeypatch.setenv("PS_DEVICE_LOCK_HELD", "1")
        # nested use under a holding parent: no flock call, reports held
        with device_lock(timeout_s=0) as a, device_lock(timeout_s=0) as b:
            assert a and b

    def test_block_after_timeout_acquires_not_skips(
        self, tmp_path, monkeypatch
    ):
        """ADVICE r3: on wait-bound expiry the bench must KEEP waiting
        and take the lock when freed — never proceed unlocked (a
        lockless bench lets the watcher collide once the holder
        exits). Holder releases 0.4s in; contender's bound is 0.1s."""
        import threading
        import time as _t

        from parameter_server_tpu.utils.device_lock import device_lock

        monkeypatch.setenv("PS_DEVICE_LOCK", str(tmp_path / "dev.lock"))
        monkeypatch.delenv("PS_DEVICE_LOCK_HELD", raising=False)
        release = threading.Event()

        def holder():
            with device_lock(timeout_s=0) as got:
                assert got
                release.wait(5)

        th = threading.Thread(target=holder)
        # flock exclusion is per-(fd); same-process threads DO contend
        # through separate device_lock() calls (each opens its own fd)
        th.start()
        _t.sleep(0.1)
        threading.Timer(0.4, release.set).start()
        with device_lock(
            timeout_s=0.1, poll_s=0.02, block_after_timeout=True
        ) as got:
            # acquired AFTER the bound because the holder released
            assert got and got.reason == "acquired"
        th.join()

    def test_priority_request_roundtrip(self, tmp_path, monkeypatch):
        """request/clear/foreign visibility: one's own request is never
        'foreign'; another pid's fresh request is; stale ages out."""
        import os
        import time as _t

        import parameter_server_tpu.utils.device_lock as dl

        monkeypatch.setenv("PS_DEVICE_LOCK", str(tmp_path / "dev.lock"))
        monkeypatch.delenv("PS_DEVICE_LOCK_HELD", raising=False)
        assert dl.foreign_priority() is None  # no marker at all
        dl.request_priority("bench")
        assert dl.foreign_priority() is None  # our own marker
        # forge another process's marker (pid+1, fresh stamp)
        with open(dl._request_path(), "w") as f:
            f.write(f"{os.getpid() + 1} {_t.time():.0f} bench\n")
        seen = dl.foreign_priority()
        assert seen and "bench" in seen
        # stale marker is ignored
        with open(dl._request_path(), "w") as f:
            f.write(f"{os.getpid() + 1} {_t.time() - 1e6:.0f} bench\n")
        assert dl.foreign_priority() is None
        # clear_priority leaves a FOREIGN marker alone
        with open(dl._request_path(), "w") as f:
            f.write(f"{os.getpid() + 1} {_t.time():.0f} bench\n")
        dl.clear_priority()
        assert dl.foreign_priority() is not None
        # ...but removes our own
        dl.request_priority("bench")
        dl.clear_priority()
        assert not os.path.exists(dl._request_path())

    def test_priority_suppressed_under_held_env(self, tmp_path, monkeypatch):
        """A lock-holder's child must not yield to its own parent's
        request marker (the bench's children run under HELD_ENV)."""
        import os
        import time as _t

        import parameter_server_tpu.utils.device_lock as dl

        monkeypatch.setenv("PS_DEVICE_LOCK", str(tmp_path / "dev.lock"))
        with open(dl._request_path(), "w") as f:
            f.write(f"{os.getpid() + 1} {_t.time():.0f} bench\n")
        monkeypatch.setenv("PS_DEVICE_LOCK_HELD", "1")
        assert dl.foreign_priority() is None

    def test_held_child_never_requests_priority(self, tmp_path, monkeypatch):
        """A process whose parent holds the flock (HELD_ENV) must not
        write a priority marker: the watcher spawning bench.py saw its
        own child's probe marker as foreign and preempted it after 6s
        (observed 2026-08-01). request_priority is a no-op under
        HELD_ENV; foreign_priority(ignore_pid=child) is the backstop."""
        import os
        import time as _t

        import parameter_server_tpu.utils.device_lock as dl

        monkeypatch.setenv("PS_DEVICE_LOCK", str(tmp_path / "dev.lock"))
        monkeypatch.setenv("PS_DEVICE_LOCK_HELD", "1")
        dl.request_priority("bench-probe")
        assert not os.path.exists(dl._request_path())
        # backstop: even if an old child binary wrote its marker, the
        # watcher ignores the pid of the child it spawned
        monkeypatch.delenv("PS_DEVICE_LOCK_HELD", raising=False)
        child_pid = os.getpid() + 1
        with open(dl._request_path(), "w") as f:
            f.write(f"{child_pid} {_t.time():.0f} bench-probe\n")
        assert dl.foreign_priority() is not None
        assert dl.foreign_priority(ignore_pid=child_pid) is None


class TestTraceSummary:
    def test_summarize_synthetic_chrome_trace(self, tmp_path):
        """summarize_trace buckets device-track complete events by
        named-scope phase (ps_* prefixes reach HLO op metadata) and
        ignores host tracks; no trace -> None."""
        import gzip
        import json

        from parameter_server_tpu.utils.profiling import summarize_trace

        assert summarize_trace(str(tmp_path)) is None

        events = [
            {"ph": "M", "pid": 1, "name": "process_name",
             "args": {"name": "/device:TPU:0"}},
            {"ph": "M", "pid": 2, "name": "process_name",
             "args": {"name": "python host threads"}},
            # device tracks: only the op-level tid counts — the
            # module-span tid covers the sum of its ops and would
            # double device_ms if included
            {"ph": "M", "pid": 1, "tid": 1, "name": "thread_name",
             "args": {"name": "XLA Ops"}},
            {"ph": "M", "pid": 1, "tid": 2, "name": "thread_name",
             "args": {"name": "XLA Modules"}},
            # device ops: args.name carries the jax.named_scope path
            {"ph": "X", "pid": 1, "tid": 1, "ts": 0, "dur": 1500,
             "name": "fusion.1",
             "args": {"name": "jit(step)/ps_pull/gather"}},
            {"ph": "X", "pid": 1, "tid": 1, "ts": 1500, "dur": 2500,
             "name": "fusion.2",
             "args": {"name": "jit(step)/ps_update/while"}},
            {"ph": "X", "pid": 1, "tid": 1, "ts": 4000, "dur": 500,
             "name": "copy.3", "args": {}},
            # module aggregate span: must NOT count
            {"ph": "X", "pid": 1, "tid": 2, "ts": 0, "dur": 4500,
             "name": "jit_mini_step", "args": {}},
            # host event on another track: must not count
            {"ph": "X", "pid": 2, "tid": 9, "ts": 0, "dur": 9e6,
             "name": "$main.py:1 run", "args": {}},
        ]
        run = tmp_path / "plugins" / "profile" / "run1"
        run.mkdir(parents=True)
        with gzip.open(run / "host.trace.json.gz", "wt") as f:
            json.dump({"traceEvents": events}, f)

        s = summarize_trace(str(tmp_path))
        assert s is not None
        assert s["device_ms"] == 4.5
        assert s["phases"]["ps_pull"] == 1.5
        assert s["phases"]["ps_update"] == 2.5
        assert s["phases"]["other"] == 0.5
        names = [o["name"] for o in s["top_ops"]]
        assert "fusion.2" in names
        assert "$main.py:1 run" not in names
        assert "jit_mini_step" not in names

    def test_nested_control_flow_spans_credit_self_time_only(
        self, tmp_path
    ):
        """A while/scan wrapper span on the op track NESTS its body ops
        as child events; the parent must be credited only its self time
        (dur minus children) or device_ms double-counts the scan body
        into a phantom 'other' bucket (observed live: while.3 248ms
        over 8 scan steps re-counted the whole step)."""
        import gzip
        import json

        from parameter_server_tpu.utils.profiling import summarize_trace

        events = [
            {"ph": "M", "pid": 1, "name": "process_name",
             "args": {"name": "/device:TPU:0"}},
            {"ph": "M", "pid": 1, "tid": 1, "name": "thread_name",
             "args": {"name": "XLA Ops"}},
            # parent scan wrapper: 10ms, of which 9ms is children
            {"ph": "X", "pid": 1, "tid": 1, "ts": 0, "dur": 10000,
             "name": "while.3", "args": {}},
            # two body iterations: a pull fusion and a nested update,
            # the update itself containing a grandchild kernel
            {"ph": "X", "pid": 1, "tid": 1, "ts": 500, "dur": 4000,
             "name": "fusion.44",
             "args": {"name": "jit(step)/ps_pull/gather"}},
            {"ph": "X", "pid": 1, "tid": 1, "ts": 5000, "dur": 5000,
             "name": "fusion.48",
             "args": {"name": "jit(step)/ps_update/scatter"}},
            {"ph": "X", "pid": 1, "tid": 1, "ts": 6000, "dur": 2000,
             "name": "ftrl_update.7",
             "args": {"name": "jit(step)/ps_update/custom_call"}},
            # op after the scan, top level
            {"ph": "X", "pid": 1, "tid": 1, "ts": 10000, "dur": 1000,
             "name": "copy.9", "args": {}},
        ]
        run = tmp_path / "plugins" / "profile" / "r"
        run.mkdir(parents=True)
        with gzip.open(run / "t.trace.json.gz", "wt") as f:
            json.dump({"traceEvents": events}, f)

        s = summarize_trace(str(tmp_path))
        assert s is not None
        # total = 10ms scan + 1ms copy, NOT 10+9+1
        assert s["device_ms"] == 11.0
        assert s["phases"]["ps_pull"] == 4.0
        # update = 5ms span, of which grandchild 2ms — both ps_update
        assert s["phases"]["ps_update"] == 5.0
        # other = scan self (1ms) + copy (1ms)
        assert s["phases"]["other"] == 2.0
        ops = {o["name"]: o["ms"] for o in s["top_ops"]}
        assert ops["while.3"] == 1.0
        assert ops["fusion.48"] == 3.0
        assert ops["ftrl_update.7"] == 2.0

    def test_summarize_newest_run_only_and_host_only_none(self, tmp_path):
        """A reused profile dir accumulates runs — only the newest
        plugins/profile/<ts> run is summed; a trace with no
        identifiable device track returns None (host wall-clock must
        never be reported as device time)."""
        import gzip
        import json
        import os
        import time as _t

        from parameter_server_tpu.utils.profiling import summarize_trace

        def write_run(name, dur, device=True):
            run = tmp_path / "plugins" / "profile" / name
            run.mkdir(parents=True)
            pname = "/device:TPU:0" if device else "host python"
            events = [
                {"ph": "M", "pid": 1, "name": "process_name",
                 "args": {"name": pname}},
                {"ph": "X", "pid": 1, "tid": 1, "ts": 0, "dur": dur,
                 "name": "fusion.9",
                 "args": {"name": "jit(f)/ps_compute/dot"}},
            ]
            with gzip.open(run / "t.trace.json.gz", "wt") as f:
                json.dump({"traceEvents": events}, f)
            return run

        old = write_run("run_old", 7000)
        _t.sleep(0.05)
        write_run("run_new", 2000)
        # age the old dir so mtime ordering is unambiguous
        os.utime(old, (1, 1))
        s = summarize_trace(str(tmp_path))
        assert s is not None and s["device_ms"] == 2.0

        host_only = tmp_path / "hostonly"
        write_host = host_only / "plugins" / "profile" / "r"
        write_host.mkdir(parents=True)
        events = [
            {"ph": "M", "pid": 5, "name": "process_name",
             "args": {"name": "python host threads"}},
            {"ph": "X", "pid": 5, "tid": 1, "ts": 0, "dur": 5e6,
             "name": "run", "args": {}},
        ]
        with gzip.open(write_host / "t.trace.json.gz", "wt") as f:
            json.dump({"traceEvents": events}, f)
        assert summarize_trace(str(host_only)) is None


class TestCompileCache:
    def test_enable_sets_config_and_opt_out(self, tmp_path, monkeypatch):
        import jax

        from parameter_server_tpu.utils import compile_cache as cc

        monkeypatch.setattr(cc, "_ENABLED_DIR", None)
        # the documented opt-out must not fail the test for devs using it
        monkeypatch.delenv("PS_NO_COMPILE_CACHE", raising=False)
        # the suite runs on CPU, where the cache is gated off by default
        monkeypatch.setenv("PS_COMPILE_CACHE_CPU", "1")
        prev = jax.config.jax_compilation_cache_dir
        # knob absent on some jax builds — the product code tolerates
        # that, so the test must too
        prev_min = getattr(
            jax.config, "jax_persistent_cache_min_compile_time_secs", None
        )
        try:
            d = str(tmp_path / "cache")
            assert cc.enable(d) == d
            assert jax.config.jax_compilation_cache_dir == d
            # idempotent
            assert cc.enable(d) == d
            # opt-out wins
            monkeypatch.setattr(cc, "_ENABLED_DIR", None)
            monkeypatch.setenv("PS_NO_COMPILE_CACHE", "1")
            assert cc.enable(d) is None
            # on the CPU backend the cache is gated off by default
            # (AOT reload SIGILL warnings) unless PS_COMPILE_CACHE_CPU
            monkeypatch.delenv("PS_NO_COMPILE_CACHE", raising=False)
            monkeypatch.delenv("PS_COMPILE_CACHE_CPU", raising=False)
            monkeypatch.setattr(cc, "_ENABLED_DIR", None)
            assert cc.enable(d) is None
        finally:
            jax.config.update("jax_compilation_cache_dir", prev)
            if prev_min is not None:
                jax.config.update(
                    "jax_persistent_cache_min_compile_time_secs", prev_min
                )


class TestRunGraceful:
    def test_sigterm_grace_then_success_exit(self):
        """A responsive child gets SIGTERM and exits inside the grace
        window; TimeoutExpired still propagates (the call did not
        finish in time) and the child is reaped."""
        import subprocess
        import sys
        import time

        from parameter_server_tpu.utils.subproc import run_graceful

        child = (
            "import signal, sys, time\n"
            "signal.signal(signal.SIGTERM, lambda *a: sys.exit(143))\n"
            "time.sleep(60)\n"
        )
        t0 = time.perf_counter()
        with pytest.raises(subprocess.TimeoutExpired):
            run_graceful([sys.executable, "-c", child], timeout_s=1.0)
        took = time.perf_counter() - t0
        assert took < 8.0  # SIGTERM honored quickly, grace not burned

    def test_stubborn_child_killed_after_grace(self, tmp_path):
        """A child that ignores SIGTERM is SIGKILLed after the grace."""
        import subprocess
        import sys
        import time

        from parameter_server_tpu.utils.subproc import run_graceful

        # the child must INSTALL SIG_IGN before the timeout fires, or
        # the SIGTERM kills it during interpreter startup and the grace
        # path never runs (took ~= timeout, not timeout+grace). Startup
        # is ~2.5s idle but unbounded under load (observed >3s with a
        # full suite sharing the one core) — escalate the startup
        # window until the SENTINEL proves SIG_IGN was installed
        # before the SIGTERM landed (a timing margin can false-pass).
        for timeout_s in (3.0, 8.0, 20.0):
            sentinel = tmp_path / f"ign_{timeout_s}"
            child = (
                "import pathlib, signal, time\n"
                "signal.signal(signal.SIGTERM, signal.SIG_IGN)\n"
                f"pathlib.Path({str(sentinel)!r}).write_text('x')\n"
                "time.sleep(60)\n"
            )
            t0 = time.perf_counter()
            with pytest.raises(subprocess.TimeoutExpired):
                run_graceful(
                    [sys.executable, "-c", child],
                    timeout_s=timeout_s, term_grace_s=1.0,
                )
            took = time.perf_counter() - t0
            if sentinel.exists():
                break  # SIG_IGN demonstrably beat the SIGTERM
        assert sentinel.exists(), "child never installed SIG_IGN"
        assert timeout_s + 0.9 < took < timeout_s + 15.0

    def test_interrupt_kills_and_reaps(self, monkeypatch):
        """On a non-timeout exception mid-communicate the child is
        killed and reaped before the exception propagates — an
        orphaned live tunnel client outliving the caller's device-lock
        scope is the two-client collision the flock prevents."""
        import os
        import subprocess
        import sys

        from parameter_server_tpu.utils import subproc

        spawned = []
        real_popen = subprocess.Popen

        class InterruptingPopen(real_popen):
            def communicate(self, *a, **kw):
                if not spawned:
                    spawned.append(self.pid)
                    raise KeyboardInterrupt
                return real_popen.communicate(self, *a, **kw)

        monkeypatch.setattr(subprocess, "Popen", InterruptingPopen)
        with pytest.raises(KeyboardInterrupt):
            subproc.run_graceful(
                [sys.executable, "-c", "import time; time.sleep(60)"],
                timeout_s=5.0,
            )
        pid = spawned[0]
        # reaped: the pid is gone (or at worst a zombie being reaped);
        # os.kill(pid, 0) raising ProcessLookupError proves exit
        for _ in range(50):
            try:
                os.kill(pid, 0)
            except ProcessLookupError:
                break
            import time as _t

            _t.sleep(0.1)
        else:
            raise AssertionError(f"child {pid} still alive after interrupt")


class TestCompileCacheHardening:
    def test_symlinked_cache_dir_is_rejected(self, tmp_path, monkeypatch):
        """A predictable /tmp cache path pre-created as a SYMLINK by
        another local user must be refused: makedirs/stat/chmod all
        follow links, so the old uid check passed while chmodding and
        writing into the attacker's chosen target."""
        from parameter_server_tpu.utils import compile_cache as cc

        monkeypatch.setattr(cc, "_ENABLED_DIR", None)
        monkeypatch.delenv("PS_NO_COMPILE_CACHE", raising=False)
        monkeypatch.setenv("PS_COMPILE_CACHE_CPU", "1")
        target = tmp_path / "victim"
        target.mkdir()
        link = tmp_path / "cache_link"
        link.symlink_to(target)
        assert cc.enable(str(link)) is None
        # enable() refused, so nothing was chmodded through the link
        # and no jax config points at it
        assert (target.stat().st_mode & 0o777) != 0o700
        import jax

        assert jax.config.jax_compilation_cache_dir != str(link)

    def test_default_platform_without_tpu_plugin_is_gated(
        self, tmp_path, monkeypatch
    ):
        """Empty JAX_PLATFORMS on a host with no accelerator plugin
        means jax silently defaults to XLA:CPU — the cache must stay
        off there (the documented SIGILL-on-reload risk)."""
        from parameter_server_tpu.utils import compile_cache as cc

        monkeypatch.setattr(cc, "_ENABLED_DIR", None)
        monkeypatch.delenv("PS_NO_COMPILE_CACHE", raising=False)
        monkeypatch.delenv("PS_COMPILE_CACHE_CPU", raising=False)
        monkeypatch.setenv("JAX_PLATFORMS", "")
        # this host HAS plugins installed; simulate a bare-CPU host at
        # the detection seam (the helper's own logic is import probes)
        monkeypatch.setattr(
            cc, "_accelerator_plugin_detectable", lambda: False
        )
        import jax

        prev = jax.config.jax_platforms
        try:
            jax.config.update("jax_platforms", None)
            assert cc.enable(str(tmp_path / "c")) is None
        finally:
            jax.config.update("jax_platforms", prev)

    def test_plugin_detection_finds_entry_points(self):
        """On THIS image libtpu is installed: the no-init detection
        must see it (a false negative silently disables the cache on
        genuine accelerator hosts)."""
        from parameter_server_tpu.utils import compile_cache as cc

        assert cc._accelerator_plugin_detectable() is True


class TestRunGracefulInterruptDuringGrace:
    def test_interrupt_in_grace_window_still_reaps(self, monkeypatch):
        """A KeyboardInterrupt raised while blocked in the grace-window
        communicate must still SIGKILL and reap the child before
        propagating (advisor r4: it escaped both handlers, leaving a
        SIGTERM'd-but-alive tunnel client orphaned)."""
        import subprocess

        from parameter_server_tpu.utils import subproc

        events = []

        class FakePopen:
            returncode = None

            def __init__(self, argv, **kw):
                self._calls = 0

            def communicate(self, timeout=None):
                self._calls += 1
                if self._calls == 1:
                    raise subprocess.TimeoutExpired("x", timeout)
                if self._calls == 2:
                    # the interrupt lands inside the grace window
                    raise KeyboardInterrupt
                events.append("reaped")
                return b"", b""

            def terminate(self):
                events.append("terminate")

            def kill(self):
                events.append("kill")

        monkeypatch.setattr(subproc.subprocess, "Popen", FakePopen)
        with pytest.raises(KeyboardInterrupt):
            subproc.run_graceful(["x"], timeout_s=0.1, term_grace_s=0.1)
        assert events == ["terminate", "kill", "reaped"]


class TestIterOnThread:
    def test_items_and_order(self):
        from parameter_server_tpu.utils.concurrent import iter_on_thread

        assert list(iter_on_thread(iter(range(20)), maxsize=3)) == list(
            range(20)
        )

    def test_producer_exception_propagates(self):
        from parameter_server_tpu.utils.concurrent import iter_on_thread

        def boom():
            yield 1
            raise ValueError("dead")

        it = iter_on_thread(boom(), maxsize=2)
        assert next(it) == 1
        with pytest.raises(ValueError, match="dead"):
            list(it)

    def test_abandonment_stops_and_joins_producer(self):
        import threading
        import time

        from parameter_server_tpu.utils.concurrent import iter_on_thread

        alive = {"n": 0}
        started = threading.Event()

        def slow():
            alive["n"] += 1
            started.wait(5)
            for i in range(1000):
                yield i
            # unreachable when abandoned early

        before = threading.active_count()
        it = iter_on_thread(slow(), maxsize=1)
        started.set()
        next(it)
        it.close()  # consumer abandons; producer must stop promptly
        t0 = time.time()
        while threading.active_count() > before and time.time() - t0 < 5:
            time.sleep(0.05)
        assert threading.active_count() <= before
