"""Fused sparse FTRL kernel (ops/ftrl_sparse.py) — parity + contracts.

The kernel's claim is BIT-identity with the XLA rows path
(``updaters.apply_state_rows`` for FTRL/decay): interpret mode runs the
same kernel body the chip compiles (minus the PRNG, substituted by the
position-hash dither the jnp reference itself draws — same
``dither_hash_u32`` stream, so even the seeded bf16 narrow is exact).
Everything the predicate rejects must fall back to the rows path,
bit-identically, so the train step can call one entry point
unconditionally.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from parameter_server_tpu.apps.linear.learning_rate import LearningRate
from parameter_server_tpu.apps.linear.penalty import ElasticNet
from parameter_server_tpu.apps.linear.updaters import (
    FTRLUpdater,
    apply_state_rows,
)
from parameter_server_tpu.ops import ftrl_sparse
from parameter_server_tpu.ops.ftrl_sparse import (
    ftrl_sparse_rows_ref,
    ftrl_sparse_update,
    resolve_update_path,
    use_sparse_kernel,
)

KW = dict(alpha=0.5, beta=1.0, l1=0.05, l2=0.01)


def _updater(dtype=jnp.float32):
    return FTRLUpdater(
        LearningRate("decay", alpha=KW["alpha"], beta=KW["beta"]),
        ElasticNet(KW["l1"], KW["l2"]),
        sqrt_n_dtype=dtype,
    )


def _state(p, rng, dtype=jnp.float32):
    return {
        "z": jnp.asarray(rng.normal(size=p).astype(np.float32)),
        "sqrt_n": jnp.asarray(
            (rng.random(p) * 2).astype(np.float32)
        ).astype(dtype),
    }


def _touch(p, u, rng, n_live=None, zero_g_at=()):
    """localize-shaped inputs: sorted unique owned ids, clip-style
    non-ok entries, sentinel tail. Returns (rel, ok, g_u) jnp arrays."""
    n_live = n_live if n_live is not None else u - max(2, u // 8)
    live = np.unique(rng.integers(0, p, n_live))
    rel = np.full(u, p - 1, np.int32)  # high-clip tail (ok False)
    rel[: len(live)] = np.sort(live).astype(np.int32)
    ok = np.zeros(u, bool)
    ok[: len(live)] = True
    g = rng.normal(size=u).astype(np.float32)
    for i in zero_g_at:
        g[i] = 0.0
    return jnp.asarray(rel), jnp.asarray(ok), jnp.asarray(g)


class TestInterpretParity:
    def test_f32_bit_exact_vs_apply_state_rows(self, rng):
        p, u = 1 << 13, 256
        up = _updater()
        st = _state(p, rng)
        rel, ok, g = _touch(p, u, rng, zero_g_at=(3,))
        want = apply_state_rows(up, st, rel, ok, g)
        zk, nk = ftrl_sparse_update(
            st["z"], st["sqrt_n"], rel, ok, g, **KW,
            force_pallas=True, interpret=True,
        )
        np.testing.assert_array_equal(np.asarray(zk), np.asarray(want["z"]))
        np.testing.assert_array_equal(
            np.asarray(nk), np.asarray(want["sqrt_n"])
        )

    def test_bf16_seeded_bit_exact_via_dither_substitute(self, rng):
        """The interpret-mode bf16 narrow replays the reference's
        position-hash dither (dither_hash_u32 indexed by each lane's
        u-position), so even the stochastic narrow is BIT-exact — not
        just neighbor-close — against apply_state_rows."""
        p, u = 1 << 13, 256
        up = _updater(jnp.bfloat16)
        st = _state(p, rng, jnp.bfloat16)
        rel, ok, g = _touch(p, u, rng)
        seed = jnp.uint32(7)
        want = apply_state_rows(up, st, rel, ok, g, seed=seed)
        zk, nk = ftrl_sparse_update(
            st["z"], st["sqrt_n"], rel, ok, g, **KW, seed=seed,
            force_pallas=True, interpret=True,
        )
        np.testing.assert_array_equal(np.asarray(zk), np.asarray(want["z"]))
        np.testing.assert_array_equal(
            np.asarray(nk).view(np.uint16),
            np.asarray(want["sqrt_n"]).view(np.uint16),
        )

    def test_whole_trajectory_serial_vs_fused(self, rng):
        """Multi-step state evolution: N serial apply_state_rows steps
        vs N fused-kernel steps over the same touch stream end
        bit-identical — the trajectory contract, not just one step."""
        p, u = 1 << 13, 128
        up = _updater()
        st_serial = _state(p, rng)
        st_fused = {k: v for k, v in st_serial.items()}
        for step in range(6):
            srng = np.random.default_rng(100 + step)
            rel, ok, g = _touch(p, u, srng)
            st_serial = apply_state_rows(up, st_serial, rel, ok, g)
            zf, nf = ftrl_sparse_update(
                st_fused["z"], st_fused["sqrt_n"], rel, ok, g, **KW,
                force_pallas=True, interpret=True,
            )
            st_fused = {"z": zf, "sqrt_n": nf}
        np.testing.assert_array_equal(
            np.asarray(st_fused["z"]), np.asarray(st_serial["z"])
        )
        np.testing.assert_array_equal(
            np.asarray(st_fused["sqrt_n"]),
            np.asarray(st_serial["sqrt_n"]),
        )

    def test_dense_rows_all_lanes(self, rng):
        """Fully dense touch (every lane of a row range) exercises the
        duplicate-row merge: many slots per 128-lane row must collapse
        into ONE fetched/written row with all lanes live."""
        p = 1 << 13
        rel = jnp.arange(512, dtype=jnp.int32)  # rows 0-3 fully dense
        ok = jnp.ones(512, bool)
        g = jnp.asarray(rng.normal(size=512).astype(np.float32))
        st = _state(p, rng)
        up = _updater()
        want = apply_state_rows(up, st, rel, ok, g)
        zk, nk = ftrl_sparse_update(
            st["z"], st["sqrt_n"], rel, ok, g, **KW,
            force_pallas=True, interpret=True,
        )
        np.testing.assert_array_equal(np.asarray(zk), np.asarray(want["z"]))
        np.testing.assert_array_equal(
            np.asarray(nk), np.asarray(want["sqrt_n"])
        )


class TestEdgeShapes:
    def test_sentinel_padding_rows_dropped(self, rng):
        """An all-sentinel batch (nothing owned) must leave the whole
        table bit-identical — clip-merged rows write back unchanged
        copies, never perturbed ones."""
        p, u = 1 << 13, 64
        st = _state(p, rng)
        rel = jnp.full((u,), p - 1, jnp.int32)
        ok = jnp.zeros((u,), bool)
        g = jnp.asarray(rng.normal(size=u).astype(np.float32))
        zk, nk = ftrl_sparse_update(
            st["z"], st["sqrt_n"], rel, ok, g, **KW,
            force_pallas=True, interpret=True,
        )
        np.testing.assert_array_equal(np.asarray(zk), np.asarray(st["z"]))
        np.testing.assert_array_equal(
            np.asarray(nk), np.asarray(st["sqrt_n"])
        )

    def test_clip_merge_does_not_perturb_shared_rows(self, rng):
        """Non-ok entries clip to row 0 / the last row; when those rows
        are ALSO genuinely touched, the zero-gradient lanes must merge
        into the genuine row group without perturbing its update."""
        p, u = 1 << 13, 64
        st = _state(p, rng)
        up = _updater()
        # rel stays NON-DECREASING (the localize-of-sorted-uslots
        # contract): low-clip non-ok entries lead, genuine rows follow
        # (row 0 and the last row among them), high-clip/sentinel tail
        rel_h = np.full(u, p - 1, np.int32)
        rel_h[:9] = [0, 0, 1, 5, 130, 200, 4000, p - 129, p - 2]
        ok_h = np.zeros(u, bool)
        ok_h[1:9] = True  # entry 0 is a low clip (ok False) onto row 0
        g = rng.normal(size=u).astype(np.float32)
        rel, ok = jnp.asarray(rel_h), jnp.asarray(ok_h)
        gj = jnp.asarray(g)
        want = apply_state_rows(up, st, rel, ok, gj)
        zk, nk = ftrl_sparse_update(
            st["z"], st["sqrt_n"], rel, ok, gj, **KW,
            force_pallas=True, interpret=True,
        )
        np.testing.assert_array_equal(np.asarray(zk), np.asarray(want["z"]))
        np.testing.assert_array_equal(
            np.asarray(nk), np.asarray(want["sqrt_n"])
        )

    def test_negative_sentinel_tail_does_not_lose_updates(self, rng):
        """The ≥2^31-slot sentinel is -1 (slot_sentinel), so localize
        clips the padding tail to rel 0 BELOW the ascending owned ids —
        rel is NOT non-decreasing there. The row dedup must not emit
        row 0 twice (a later stale-fetch write-back would silently
        erase the genuine row-0 update — the review-confirmed bug
        shape): remapping non-ok rows through the ok-row running max
        keeps the sequence monotone, and slots in row 0 keep their
        updates bit-exactly."""
        p, u = 1 << 13, 64
        st = _state(p, rng)
        up = _updater()
        rel_h = np.zeros(u, np.int32)
        # genuine ascending ids, rows 0 and upward among them
        rel_h[:8] = [5, 9, 140, 300, 2000, 4096, 8000, p - 1]
        ok_h = np.zeros(u, bool)
        ok_h[:8] = True
        # the -1 sentinel tail clipped to 0 (ok False) AFTER the
        # ascending ids — out of order by construction
        g = rng.normal(size=u).astype(np.float32)
        rel, ok, gj = jnp.asarray(rel_h), jnp.asarray(ok_h), jnp.asarray(g)
        want = apply_state_rows(up, st, rel, ok, gj)
        zk, nk = ftrl_sparse_update(
            st["z"], st["sqrt_n"], rel, ok, gj, **KW,
            force_pallas=True, interpret=True, block_rows=8,
        )
        # the genuine row-0 slots (5, 9) must carry their updates
        assert np.asarray(zk)[5] != np.asarray(st["z"])[5]
        np.testing.assert_array_equal(np.asarray(zk), np.asarray(want["z"]))
        np.testing.assert_array_equal(
            np.asarray(nk), np.asarray(want["sqrt_n"])
        )

    def test_non_tile_multiple_row_count_falls_back(self, rng):
        """u % 8 != 0 cannot be tiled: the predicate rejects it and the
        entry point must return the rows-path result bit-identically
        (even under force_pallas — never onto an untileable shape)."""
        p, u = 1 << 13, 12
        assert not use_sparse_kernel(p, u, False, True, True)
        st = _state(p, rng)
        rel, ok, g = _touch(p, u, rng, n_live=8)
        want = ftrl_sparse_rows_ref(
            st["z"], st["sqrt_n"], rel, ok, g, **KW
        )
        got = ftrl_sparse_update(
            st["z"], st["sqrt_n"], rel, ok, g, **KW,
            force_pallas=True, interpret=True,
        )
        np.testing.assert_array_equal(np.asarray(got[0]), np.asarray(want[0]))
        np.testing.assert_array_equal(np.asarray(got[1]), np.asarray(want[1]))

    def test_non_tileable_table_falls_back(self, rng):
        p = (1 << 13) + 128  # not a multiple of 8*128
        assert not use_sparse_kernel(p, 64, False, True, True)

    def test_unseeded_bf16_falls_back(self):
        assert not use_sparse_kernel(1 << 13, 64, True, False, True)
        assert use_sparse_kernel(1 << 13, 64, True, True, True)

    def test_duplicate_uslots_contract_asserted(self, rng):
        """apply_state_rows' duplicate-free contract is ASSERTED on
        concrete host inputs: a duplicated ok row would double-apply
        nonlinearly in every formulation."""
        p = 1 << 13
        up = _updater()
        st = _state(p, rng)
        rel = np.asarray([3, 3, 7, 9, 10, 11, 12, 13], np.int32)
        ok = np.ones(8, bool)
        g = np.ones(8, np.float32)
        with pytest.raises(AssertionError, match="duplicate-free"):
            apply_state_rows(up, st, rel, ok, g)

    def test_block_rows_env_and_arg(self, rng, monkeypatch):
        """Block-size resolution: explicit arg wins, env override
        applies, non-dividing values round down — and every block size
        is bit-identical (the grid carve cannot change results)."""
        p, u = 1 << 13, 256
        st = _state(p, rng)
        rel, ok, g = _touch(p, u, rng)
        base = ftrl_sparse_update(
            st["z"], st["sqrt_n"], rel, ok, g, **KW,
            force_pallas=True, interpret=True,
        )
        for br in (8, 32, 256):
            got = ftrl_sparse_update(
                st["z"], st["sqrt_n"], rel, ok, g, **KW,
                force_pallas=True, interpret=True, block_rows=br,
            )
            np.testing.assert_array_equal(
                np.asarray(got[0]), np.asarray(base[0]), err_msg=str(br)
            )
        monkeypatch.setenv("PS_FTRL_SPARSE_BLOCK_ROWS", "64")
        assert ftrl_sparse._sparse_block_rows(256) == 64
        assert ftrl_sparse._sparse_block_rows(256, 32) == 32
        # non-dividing request rounds down to a dividing power of two
        assert ftrl_sparse._sparse_block_rows(24, 512) == 8


@pytest.mark.slow
class TestHeavySweep:
    """Broader shape/block sweep — interpret mode over bigger tables is
    minutes-scale on this 2-core host, so it rides outside tier-1
    (ROADMAP 870s budget); `pytest -m slow` runs it."""

    @pytest.mark.parametrize("dtype,seed", [
        (jnp.float32, None), (jnp.bfloat16, 11),
    ], ids=["f32", "bf16"])
    @pytest.mark.parametrize("u", [1024, 4096])
    def test_parity_sweep(self, rng, dtype, seed, u):
        p = 1 << 16
        up = _updater(dtype)
        st = _state(p, rng, dtype)
        rel, ok, g = _touch(p, u, rng)
        sj = None if seed is None else jnp.uint32(seed)
        want = apply_state_rows(up, st, rel, ok, g, seed=sj)
        for br in (128, 1024):
            zk, nk = ftrl_sparse_update(
                st["z"], st["sqrt_n"], rel, ok, g, **KW, seed=sj,
                force_pallas=True, interpret=True, block_rows=br,
            )
            np.testing.assert_array_equal(
                np.asarray(zk), np.asarray(want["z"]), err_msg=str(br)
            )
            np.testing.assert_array_equal(
                np.asarray(nk).view(
                    np.uint16 if dtype == jnp.bfloat16 else np.float32
                ),
                np.asarray(want["sqrt_n"]).view(
                    np.uint16 if dtype == jnp.bfloat16 else np.float32
                ),
                err_msg=str(br),
            )


class TestPathResolution:
    def test_predicate_off_tpu(self):
        # off-TPU without force: never the kernel (this container)
        assert not use_sparse_kernel(1 << 13, 256, False, True, False)

    def test_resolve_update_path_names(self):
        assert resolve_update_path(
            "sparse", on_tpu=True, shard=1 << 20, u=1024,
            bf16_n=False, has_seed=True,
        ) == "pallas_sparse"
        assert resolve_update_path(
            "sparse", on_tpu=False, shard=1 << 20, u=1024,
            bf16_n=False, has_seed=True,
        ) == "xla_rows"
        # non-tileable unique width: sparse mode falls to the rows path
        assert resolve_update_path(
            "sparse", on_tpu=True, shard=1 << 20, u=1023,
            bf16_n=False, has_seed=True,
        ) == "xla_rows"
        # dense mode on this CPU container resolves to the jnp ref
        assert resolve_update_path(
            "dense", on_tpu=False, shard=1 << 20, u=0,
            bf16_n=False, has_seed=True,
        ) == "ref"

    def test_worker_dispatch_counters(self, mesh8):
        """A sparse-mode training run ticks ps_ftrl_update_path_total
        {path=xla_rows} (this CPU container's resolution) and
        ps_ftrl_rows_total by the deduped gather width per ministep."""
        from parameter_server_tpu.apps.linear.config import (
            Config,
            LearningRateConfig,
            PenaltyConfig,
            SGDConfig,
        )
        from parameter_server_tpu.apps.linear.async_sgd import (
            AsyncSGDWorker,
        )
        from parameter_server_tpu.system.postoffice import Postoffice
        from parameter_server_tpu.telemetry import registry as telreg
        from parameter_server_tpu.utils.sparse import random_sparse

        Postoffice.reset()
        try:
            conf = Config()
            conf.penalty = PenaltyConfig(type="l1", lambda_=[0.05])
            conf.learning_rate = LearningRateConfig(
                type="decay", alpha=0.5, beta=1.0
            )
            conf.async_sgd = SGDConfig(
                algo="ftrl", minibatch=256, num_slots=1 << 14,
                max_delay=0, update="sparse",
            )
            worker = AsyncSGDWorker(conf, mesh=mesh8)
            for i in range(3):
                worker.process_minibatch(random_sparse(256, 512, 8, seed=i))
            worker.executor.wait_all()
            snap = telreg.default_registry().snapshot()
            paths = snap["ps_ftrl_update_path_total"]["values"]
            assert paths.get("path=xla_rows", 0) == 3
            rows = snap["ps_ftrl_rows_total"]["values"].get("", 0)
            assert rows > 0 and rows % 3 == 0
        finally:
            Postoffice.reset()
