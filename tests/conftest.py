"""Test harness: force a virtual 8-device CPU mesh before jax initializes.

Mirrors the reference's ``local.sh`` multi-process test launcher
(src/test/*.cc run with N servers + M workers): here the "nodes" are 8
virtual XLA CPU devices, so every sharding/collective path is exercised
without TPU hardware.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def mesh8():
    from parameter_server_tpu.parallel import mesh as meshlib

    assert len(jax.devices()) == 8, jax.devices()
    return meshlib.make_mesh(num_data=4, num_server=2)


@pytest.fixture()
def rng():
    return np.random.default_rng(0)
