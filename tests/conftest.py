"""Test harness: force a virtual 8-device CPU mesh before jax initializes.

Mirrors the reference's ``local.sh`` multi-process test launcher
(src/test/*.cc run with N servers + M workers): here the "nodes" are 8
virtual XLA CPU devices, so every sharding/collective path is exercised
without TPU hardware.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def mesh8():
    from parameter_server_tpu.parallel import mesh as meshlib

    assert len(jax.devices()) == 8, jax.devices()
    return meshlib.make_mesh(num_data=4, num_server=2)


@pytest.fixture()
def rng():
    return np.random.default_rng(0)


def require_native(symbol: str = None):
    """The ONE require-or-skip gate for native-library tests: returns
    the loaded libpsnative handle, skipping gracefully when it (or the
    named ``symbol``) is absent — unless PS_REQUIRE_NATIVE=1 (`make
    native-test`), which turns the skip into a loud failure."""
    from parameter_server_tpu.cpp import native

    lib = native()
    missing = lib is None or (
        symbol is not None and getattr(lib, symbol, None) is None
    )
    if missing:
        what = f"libpsnative.so ({symbol})" if symbol else "libpsnative.so"
        if os.environ.get("PS_REQUIRE_NATIVE"):
            pytest.fail(
                f"PS_REQUIRE_NATIVE=1 but {what} is unavailable — run "
                "`make native` (the tier-1 suite skips gracefully; this "
                "environment promised the library)"
            )
        pytest.skip(f"{what} unavailable (graceful tier-1 skip)")
    return lib
