"""End-to-end async SGD tests: FTRL parity vs a NumPy oracle of the
reference's FTRLEntry math, AdaGrad, bounded delay, reader pipeline, config
parsing. Mirrors the role of the reference's example/linear rcv1 runs."""

import numpy as np
import pytest

from parameter_server_tpu.apps.linear.async_sgd import AsyncSGDWorker
from parameter_server_tpu.apps.linear.config import (
    Config,
    LearningRateConfig,
    PenaltyConfig,
    SGDConfig,
    parse_conf,
)
from parameter_server_tpu.learner.sgd import MinibatchReader
from parameter_server_tpu.parameter.parameter import KeyDirectory
from parameter_server_tpu.system.postoffice import Postoffice
from parameter_server_tpu.utils.sparse import random_sparse


@pytest.fixture(autouse=True)
def fresh_po():
    Postoffice.reset()
    yield
    Postoffice.reset()


def make_conf(algo="ftrl", ada_grad=True, num_slots=512, max_delay=0, alpha=0.5):
    conf = Config()
    conf.penalty = PenaltyConfig(type="l1", lambda_=[0.01])
    conf.learning_rate = LearningRateConfig(type="decay", alpha=alpha, beta=1.0)
    conf.async_sgd = SGDConfig(
        algo=algo, ada_grad=ada_grad, minibatch=256, num_slots=num_slots,
        max_delay=max_delay,
    )
    return conf


def synth(n_batches, w_true, seed0=0):
    for i in range(n_batches):
        yield random_sparse(256, 512, 8, seed=seed0 + i, w_true=w_true)


@pytest.fixture(scope="module")
def w_true():
    rng = np.random.default_rng(0)
    return (rng.normal(size=512) * (rng.random(512) < 0.2)).astype(np.float32)


def ftrl_oracle(n_batches, w_true, alpha=0.5, beta=1.0, l1=0.01, l2=0.0):
    """The reference FTRLEntry::Set math (async_sgd.h:131-151), dense numpy."""
    z = np.zeros(512)
    n = np.zeros(512)

    def w_from():
        eta = alpha / (n + beta)
        zt = -z * eta
        return np.sign(zt) * np.maximum(np.abs(zt) - l1 * eta, 0) / (1 + l2 * eta)

    for i in range(n_batches):
        b = random_sparse(256, 512, 8, seed=i, w_true=w_true)
        w = w_from()
        X = b.to_dense()
        xw = X @ w
        tau = 1 / (1 + np.exp(b.y * xw))
        g = X.T @ (-b.y * tau)
        n_new = np.sqrt(n * n + g * g)
        z += g - (n_new - n) / alpha * w
        n = n_new
    return w_from()


class TestFTRLParity:
    def test_matches_reference_math(self, mesh8, w_true):
        worker = AsyncSGDWorker(make_conf(), mesh=mesh8)
        worker.directory = KeyDirectory(worker.num_slots, keys=np.arange(512))
        for batch in synth(10, w_true):
            worker.collect(worker.process_minibatch(batch))
        w_oracle = ftrl_oracle(10, w_true)
        np.testing.assert_allclose(
            worker.weights_dense()[:512], w_oracle, atol=2e-5
        )

    def test_l1_induces_sparsity(self, mesh8, w_true):
        def nnz_with(lambda1):
            conf = make_conf()
            conf.penalty = PenaltyConfig(type="l1", lambda_=[lambda1])
            worker = AsyncSGDWorker(conf, mesh=mesh8)
            worker.directory = KeyDirectory(worker.num_slots, keys=np.arange(512))
            for batch in synth(10, w_true):
                worker.collect(worker.process_minibatch(batch))
            return (worker.weights_dense() != 0).mean()

        sparse_frac, dense_frac = nnz_with(5.0), nnz_with(0.001)
        assert sparse_frac < 0.6 * dense_frac  # heavier l1 -> markedly sparser


class TestConvergence:
    def test_ftrl_converges(self, mesh8, w_true):
        worker = AsyncSGDWorker(make_conf(num_slots=4096), mesh=mesh8)
        prog = worker.train(synth(40, w_true))
        ev = worker.evaluate(random_sparse(2000, 512, 8, seed=999, w_true=w_true))
        assert ev["auc"] > 0.65
        assert ev["logloss"] < 0.68  # below chance log(2)
        assert prog.num_examples_processed == 40 * 256

    def test_ftrl_bf16_sqrt_n_tracks_f32(self, mesh8, w_true):
        """ftrl_state_dtype='bfloat16' (12 B/slot instead of 16 — the
        2^31 single-chip lever): sqrt_n mantissa loss perturbs only
        the per-coordinate step-size schedule, so the final logloss
        must track the f32 run closely and the state dtype must
        actually be bf16."""
        import jax.numpy as jnp

        evs = {}
        for dt in ("float32", "bfloat16"):
            conf = make_conf(num_slots=4096)
            conf.async_sgd.ftrl_state_dtype = dt
            worker = AsyncSGDWorker(conf, mesh=mesh8)
            assert worker.state["sqrt_n"].dtype == jnp.dtype(dt)
            worker.train(synth(40, w_true))
            evs[dt] = worker.evaluate(
                random_sparse(2000, 512, 8, seed=999, w_true=w_true)
            )
        assert evs["bfloat16"]["logloss"] < 0.68
        assert abs(
            evs["bfloat16"]["logloss"] - evs["float32"]["logloss"]
        ) < 5e-3, evs

    def test_bf16_sqrt_n_no_absorption_stall(self):
        """Stochastic rounding keeps the bf16 accumulator moving: with
        deterministic truncation, sqrt(n^2+g^2) rounds back to n once
        n > ~16|g| (bf16's 8-bit mantissa) and the per-coordinate LR
        stops decaying forever. 4000 constant-gradient updates must
        reach ~sqrt(T)|g| like f32, far past the ~8.0 stall point."""
        import jax.numpy as jnp

        from parameter_server_tpu.apps.linear.learning_rate import (
            LearningRate,
        )
        from parameter_server_tpu.apps.linear.penalty import ElasticNet
        from parameter_server_tpu.apps.linear.updaters import FTRLUpdater

        lr = LearningRate("decay", alpha=0.1, beta=1.0)
        upd = FTRLUpdater(lr, ElasticNet(0.0, 0.0),
                          sqrt_n_dtype="bfloat16")
        state = upd.init(8)
        g = jnp.full(8, 0.5, jnp.float32)
        touched = jnp.ones(8, bool)
        for i in range(4000):
            state = upd.apply(state, g, touched, seed=np.uint32(i))
        n = float(np.asarray(state["sqrt_n"].astype(jnp.float32))[0])
        expect = float(np.sqrt(4000) * 0.5)  # f32 trajectory ~31.6
        assert n > 25.0, (
            f"bf16 sqrt_n stalled at {n} (absorption); expected ~{expect}"
        )
        assert n < 1.3 * expect, f"bf16 sqrt_n overshot: {n} vs {expect}"

    def test_adagrad_converges(self, mesh8, w_true):
        worker = AsyncSGDWorker(
            make_conf(algo="standard", ada_grad=True, num_slots=4096), mesh=mesh8
        )
        worker.train(synth(40, w_true))
        ev = worker.evaluate(random_sparse(2000, 512, 8, seed=999, w_true=w_true))
        assert ev["auc"] > 0.65

    def test_bounded_delay_still_converges(self, mesh8, w_true):
        worker = AsyncSGDWorker(make_conf(num_slots=4096, max_delay=3), mesh=mesh8)
        worker.train(synth(40, w_true))
        ev = worker.evaluate(random_sparse(2000, 512, 8, seed=999, w_true=w_true))
        assert ev["auc"] > 0.6  # staleness costs a little, must still learn

    def test_save_model(self, mesh8, w_true, tmp_path):
        worker = AsyncSGDWorker(make_conf(num_slots=4096), mesh=mesh8)
        worker.train(synth(5, w_true))
        path = tmp_path / "model.txt"
        files = worker.save_model(str(path))
        # one file per server shard, reference naming: model.txt_S0, _S1...
        assert files and all(f.startswith(str(path) + "_S") for f in files)
        lines = [
            line
            for f in files
            for line in open(f).read().strip().splitlines()
            if not line.startswith("#")
        ]
        assert len(lines) > 10
        key, val = lines[0].split("\t")
        assert float(val) != 0


class TestReaderPipeline:
    def test_libsvm_file_to_training(self, mesh8, w_true, tmp_path):
        # write libsvm, read through MinibatchReader, train
        path = tmp_path / "train.libsvm"
        with open(path, "w") as f:
            for b in synth(4, w_true):
                dense = b.to_dense()
                for i in range(b.n):
                    lo, hi = b.indptr[i], b.indptr[i + 1]
                    # sorted ids: the parser is reference-strict and
                    # drops lines with out-of-order feature ids
                    order = np.argsort(b.indices[lo:hi], kind="stable")
                    feats = " ".join(
                        f"{int(k)}:{v:.4f}"
                        for k, v in zip(
                            b.indices[lo:hi][order], b.values[lo:hi][order]
                        )
                    )
                    f.write(f"{int(b.y[i])} {feats}\n")
        worker = AsyncSGDWorker(make_conf(num_slots=4096), mesh=mesh8)
        with MinibatchReader(files=[str(path)], minibatch_size=256) as reader:
            prog = worker.train(iter(reader))
        assert prog.num_examples_processed == 4 * 256

    def test_tail_filter_reduces_features(self, mesh8, w_true):
        batches = list(synth(3, w_true))
        reader = MinibatchReader(batches=iter(batches))
        reader.init_filter(1 << 14, 2, freq=100)  # absurd threshold drops all
        with reader:
            out = reader.read()
        assert out.nnz < batches[0].nnz


class TestConfParsing:
    def test_reference_style_conf(self):
        text = """
        # L1 logistic regression
        training_data {
          format: TEXT
          text: LIBSVM
          file: "data/rcv1_train"
        }
        loss { type: LOGIT }
        penalty { type: L1 lambda: 1 lambda: 0.1 }
        learning_rate { type: DECAY alpha: 1 beta: 1 }
        async_sgd {
          algo: FTRL
          minibatch: 10000
          max_delay: 4
          tail_feature_freq: 4
        }
        """
        cfg = parse_conf(text)
        assert cfg.training_data.file == ["data/rcv1_train"]
        assert cfg.loss.type == "logit"
        assert cfg.penalty.lambda_ == [1.0, 0.1]
        assert cfg.async_sgd.minibatch == 10000
        assert cfg.async_sgd.max_delay == 4

    def test_darlin_conf(self):
        text = """
        darlin {
          feature_block_ratio: 4
          max_block_delay: 2
          max_pass_of_data: 20
          epsilon: 2e-5
        }
        """
        cfg = parse_conf(text)
        assert cfg.darlin.max_block_delay == 2
        assert cfg.darlin.num_data_pass == 20
        assert cfg.darlin.epsilon == 2e-5


class TestU24Wire:
    def test_pack_unpack_roundtrip(self):
        import jax
        from parameter_server_tpu.apps.linear.async_sgd import pack_u24, unpack_u24

        idx = np.random.default_rng(0).integers(0, 1 << 24, size=(64, 7)).astype(np.int32)
        packed = pack_u24(idx)
        assert packed.dtype == np.uint8 and packed.shape == (64, 7, 3)
        out = np.asarray(jax.jit(unpack_u24)(packed))
        np.testing.assert_array_equal(out, idx)

    def test_packed_step_matches_unpacked(self, mesh8, w_true):
        """u24 wire format is a pure encoding: same state evolution."""

        def train(wire):
            conf = make_conf(num_slots=4096)
            conf.async_sgd.ell_lanes = 8
            conf.async_sgd.wire_u24 = wire
            worker = AsyncSGDWorker(conf, mesh=mesh8)
            worker.train(synth(5, w_true))
            return worker.weights_dense()

        np.testing.assert_allclose(train(True), train(False), atol=1e-6)


def synth_binary(n_batches, w_true, seed0=0):
    for i in range(n_batches):
        yield random_sparse(256, 512, 8, seed=seed0 + i, w_true=w_true, binary=True)


class TestBitsWire:
    """wire="bits": minimal bitstream encoding (slot/label bits, counts)."""

    def _train(self, mesh8, w_true, wire):
        conf = make_conf(num_slots=4096)
        conf.async_sgd.ell_lanes = 8
        conf.async_sgd.wire = wire
        worker = AsyncSGDWorker(conf, mesh=mesh8)
        worker.train(synth_binary(5, w_true))
        return worker.weights_dense()

    def test_bits_step_matches_i32(self, mesh8, w_true):
        """bits wire is a pure encoding: identical state evolution."""
        np.testing.assert_allclose(
            self._train(mesh8, w_true, "bits"),
            self._train(mesh8, w_true, "i32"),
            atol=1e-6,
        )

    def test_bits_prep_emits_bits_batch(self, mesh8, w_true):
        from parameter_server_tpu.apps.linear.async_sgd import ELLBitsBatch

        conf = make_conf(num_slots=4096)
        conf.async_sgd.ell_lanes = 8
        conf.async_sgd.wire = "bits"
        worker = AsyncSGDWorker(conf, mesh=mesh8)
        batch = next(synth_binary(1, w_true))
        prepped = worker.prep(batch, device_put=False)
        assert isinstance(prepped, ELLBitsBatch)
        assert prepped.num_examples == 256

    def test_valued_batch_falls_back_to_u24(self, mesh8, w_true):
        """Non-binary data can't ride the bits wire; prep must degrade to
        the sentinel-carrying u24 format, not fail."""
        from parameter_server_tpu.apps.linear.async_sgd import ELLPackedBatch

        conf = make_conf(num_slots=4096)
        conf.async_sgd.ell_lanes = 8
        conf.async_sgd.wire = "bits"
        worker = AsyncSGDWorker(conf, mesh=mesh8)
        batch = next(synth(1, w_true))  # valued features
        prepped = worker.prep(batch, device_put=False)
        assert isinstance(prepped, ELLPackedBatch)


class TestLiveReplication:
    """VERDICT r1 #5: ongoing server replication — every replica_every
    steps the table mirrors onto the neighbor shard, so a dead server
    loses at most replica_every steps (ref Parameter::SetReplica/Recover,
    FLAGS_num_replicas)."""

    def _worker(self, mesh8, every=2):
        conf = make_conf(num_slots=512)
        conf.async_sgd.num_replicas = 1
        conf.async_sgd.replica_every = every
        return AsyncSGDWorker(conf, mesh=mesh8)

    def test_recover_restores_dead_shard_with_bounded_staleness(
        self, mesh8, w_true
    ):
        worker = self._worker(mesh8, every=1)  # replica refreshed per step
        worker.train(synth(4, w_true))
        before = worker.weights_dense()
        n_servers = 2  # mesh8 is data4 x server2
        per = worker.num_slots // n_servers
        # shard 0 dies: replacement boots empty
        worker.wipe_server_shard(0)
        wiped = worker.weights_dense()
        assert np.abs(wiped[:per]).sum() == 0
        assert worker.recover_server_shard(0)
        after = worker.weights_dense()
        # segment 1 untouched; segment 0 restored from the live replica
        # (with every=1 the replica is exactly current)
        np.testing.assert_allclose(after[per:], before[per:], atol=1e-6)
        np.testing.assert_allclose(after[:per], before[:per], atol=1e-6)

    def test_staleness_bounded_not_zero(self, mesh8, w_true):
        worker = self._worker(mesh8, every=1000)  # replicate only at step 1
        batches = list(synth(5, w_true))
        worker.train(iter(batches[:1]))  # replica taken at first step
        snap = worker.weights_dense().copy()
        worker.train(iter(batches[1:]))
        worker.wipe_server_shard(0)
        assert worker.recover_server_shard(0)
        after = worker.weights_dense()
        per = worker.num_slots // 2
        # restored segment equals the FIRST-step snapshot (stale but
        # bounded), not zeros and not the final state
        np.testing.assert_allclose(after[:per], snap[:per], atol=1e-6)

    def test_recovery_coordinator_drives_shard_recovery(self, mesh8, w_true):
        from parameter_server_tpu.system.heartbeat import (
            HeartbeatCollector,
            HeartbeatReport,
        )
        from parameter_server_tpu.system.recovery import RecoveryCoordinator

        worker = self._worker(mesh8, every=1)
        worker.train(synth(3, w_true))
        want = worker.weights_dense().copy()
        worker.wipe_server_shard(1)

        c = HeartbeatCollector(timeout=5.0)
        c.report("S1", HeartbeatReport())
        rc = RecoveryCoordinator(c)
        rc.on_server_dead(
            lambda nid: worker.recover_server_shard(int(nid[1:]))
        )
        assert rc.check(now=c._last_seen["S1"] + 6) == ["S1"]
        np.testing.assert_allclose(worker.weights_dense(), want, atol=1e-6)

    def test_no_replica_configured_returns_false(self, mesh8, w_true):
        conf = make_conf(num_slots=512)
        worker = AsyncSGDWorker(conf, mesh=mesh8)
        worker.train(synth(1, w_true))
        assert not worker.recover_server_shard(0)


class TestELLOverflowGuard:
    """VERDICT r1 #7: the reference never drops features — a row wider than
    the ELL lane budget must fall back to the hashed COO path (or raise),
    never silently truncate."""

    def test_overwide_row_falls_back_to_coo(self, mesh8, w_true):
        from parameter_server_tpu.apps.linear.async_sgd import HashedBatch

        conf = make_conf(num_slots=4096)
        conf.async_sgd.ell_lanes = 8
        worker = AsyncSGDWorker(conf, mesh=mesh8)
        wide = random_sparse(64, 512, 12, seed=3, w_true=w_true)  # 12 > 8 lanes
        prepped = worker.prep(wide, device_put=False)
        assert isinstance(prepped, HashedBatch), "must not truncate to ELL"

    def test_overwide_row_trains_all_features(self, mesh8, w_true):
        conf = make_conf(num_slots=4096)
        conf.async_sgd.ell_lanes = 8
        worker = AsyncSGDWorker(conf, mesh=mesh8)
        worker.train(iter([random_sparse(128, 512, 12, seed=4, w_true=w_true)]))
        assert worker.progress.num_examples_processed == 128

    def test_prep_batch_ell_raises_not_truncates(self, mesh8, w_true):
        from parameter_server_tpu.apps.linear.async_sgd import prep_batch_ell
        from parameter_server_tpu.parameter.parameter import KeyDirectory

        wide = random_sparse(16, 64, 12, seed=5, w_true=None)
        with pytest.raises(ValueError, match="drop"):
            prep_batch_ell(wide, KeyDirectory(1024, hashed=True), 1, 16, 8, 1024)


class TestQuantizedPush:
    """FIXING_FLOAT push filter → stochastic n-byte gradient reduce
    (ref filter/fixing_float.h applied to the push wire)."""

    def _train(self, mesh8, w_true, num_bytes, ell=True, seed0=0):
        conf = make_conf(num_slots=4096)
        if ell:
            conf.async_sgd.ell_lanes = 8
        if num_bytes:
            conf.async_sgd.push_filter = [
                {"type": "fixing_float", "num_bytes": num_bytes}
            ]
        worker = AsyncSGDWorker(conf, mesh=mesh8)
        worker.train(synth_binary(8, w_true, seed0=seed0))
        return worker

    def test_two_byte_quant_tracks_exact(self, mesh8, w_true):
        wq = self._train(mesh8, w_true, 2).weights_dense()
        we = self._train(mesh8, w_true, 0).weights_dense()
        # 16-bit fixed point: same support, small coordinate error
        err = np.abs(wq - we).max()
        assert err < 0.05, err
        assert err > 0, "quantization had no effect at all"

    def test_one_byte_quant_still_converges(self, mesh8, w_true):
        w = self._train(mesh8, w_true, 1)
        first = w.progress.objective[0] / 256
        # fresh worker to measure final logloss on the SAME stream
        prog = w.train(synth_binary(4, w_true, seed0=100))
        last = prog.objective[-1] / max(1, prog.num_examples_processed)
        assert last < first, (first, last)

    def test_conf_parses_push_filter(self):
        from parameter_server_tpu.apps.linear.config import parse_conf

        conf = parse_conf(
            """
            async_sgd {
              algo: FTRL
              push_filter { type: KEY_CACHING }
              push_filter { type: FIXING_FLOAT num_bytes: 1 }
            }
            """
        )
        types = [f["type"] for f in conf.async_sgd.push_filter]
        assert types == ["key_caching", "fixing_float"]

    def test_nonell_path_quantizes_too(self, mesh8, w_true):
        w = self._train(mesh8, w_true, 2, ell=False)
        assert w._push_quant == 2
        assert np.isfinite(w.weights_dense()).all()


class TestQuantizedPull:
    """FIXING_FLOAT pull_filter → servers quantize derived weights."""

    def test_pull_quant_converges_and_differs(self, mesh8, w_true):
        def train(pull_bytes):
            conf = make_conf(num_slots=4096)
            conf.async_sgd.ell_lanes = 8
            if pull_bytes:
                conf.async_sgd.pull_filter = [
                    {"type": "fixing_float", "num_bytes": pull_bytes}
                ]
            worker = AsyncSGDWorker(conf, mesh=mesh8)
            worker.train(synth_binary(8, w_true))
            return worker.weights_dense()

        wq, we = train(2), train(0)
        err = np.abs(wq - we).max()
        assert 0 < err < 0.05, err

    def test_bad_num_bytes_rejected(self, mesh8):
        conf = make_conf()
        conf.async_sgd.push_filter = [{"type": "fixing_float", "num_bytes": 4}]
        with pytest.raises(ValueError, match="num_bytes"):
            AsyncSGDWorker(conf, mesh=mesh8)


class TestCheckpointResume:
    """Full-state checkpoint → crash → restore → bit-identical resume
    (ref save_model_every_n_iter + Parameter::Recover)."""

    def test_resume_is_bit_identical(self, mesh8, w_true, tmp_path):
        from parameter_server_tpu.parameter.replica import CheckpointManager

        mgr = CheckpointManager(str(tmp_path))

        def fresh():
            conf = make_conf(num_slots=4096)
            conf.async_sgd.ell_lanes = 8
            return AsyncSGDWorker(conf, mesh=mesh8)

        # uninterrupted run: 5 + 3 batches
        w1 = fresh()
        w1.train(synth_binary(5, w_true))
        w1.checkpoint(mgr, step=5)
        w1.train(synth_binary(3, w_true, seed0=50))
        want = w1.weights_dense()

        # "crash": brand-new worker, restore, replay the same tail
        w2 = fresh()
        assert w2.restore(mgr) == 5
        w2.train(synth_binary(3, w_true, seed0=50))
        np.testing.assert_array_equal(w2.weights_dense(), want)


class TestScanSuperbatch:
    """Scan-fused superstep (ELLBitsSuperBatch): T minibatches in one
    launch must produce the same model as T sequential delay-0 steps."""

    def _conf(self):
        conf = make_conf(num_slots=2048)
        conf.async_sgd.ell_lanes = 8
        conf.async_sgd.wire = "bits"
        conf.async_sgd.minibatch = 256
        return conf

    def _batches(self, w_true, n):
        return [
            random_sparse(256, 512, 8, seed=100 + i, w_true=w_true, binary=True)
            for i in range(n)
        ]

    def test_matches_sequential_steps(self, mesh8, w_true):
        batches = self._batches(w_true, 6)
        seq = AsyncSGDWorker(self._conf(), mesh=mesh8, name="seq")
        for b in batches:
            seq.collect(seq.process_minibatch(b))
        Postoffice.reset()
        Postoffice.instance().start()
        fused = AsyncSGDWorker(self._conf(), mesh=mesh8, name="fused")
        prog = fused.collect(fused.submit_superbatch(batches))
        np.testing.assert_allclose(
            fused.weights_dense(), seq.weights_dense(), atol=1e-6
        )
        assert prog.num_examples_processed == 6 * 256

    def test_aux_metrics_fold(self, mesh8, w_true):
        batches = self._batches(w_true, 3)
        worker = AsyncSGDWorker(self._conf(), mesh=mesh8)
        prog = worker.collect(worker.submit_superbatch(batches, with_aux=True))
        assert prog.num_examples_processed == 3 * 256
        assert prog.auc and 0.0 <= prog.auc[-1] <= 1.0

    def test_mixed_with_single_steps(self, mesh8, w_true):
        batches = self._batches(w_true, 4)
        worker = AsyncSGDWorker(self._conf(), mesh=mesh8)
        worker.collect(worker.process_minibatch(batches[0]))
        worker.collect(worker.submit_superbatch(batches[1:3]))
        worker.collect(worker.process_minibatch(batches[3]))
        ev = worker.evaluate(
            random_sparse(1000, 512, 8, seed=999, w_true=w_true, binary=True)
        )
        assert np.isfinite(ev["logloss"])


class TestBitsWireHashModulus:
    def test_bits_wire_matches_directory_slots_with_padding(self, mesh8):
        """Regression: with a table whose padded size differs from the
        configured slot count (1001 -> 1002 over 2 servers), the bits
        wire must hash with the directory's CONFIGURED modulus — the
        same key->slot map as every other path."""
        from parameter_server_tpu.apps.linear.async_sgd import (
            ELLBitsBatch,
            unpack_bits,
        )
        from parameter_server_tpu.utils.bitpack import slot_bits

        conf = make_conf(num_slots=1001)
        conf.async_sgd.ell_lanes = 8
        conf.async_sgd.wire = "bits"
        worker = AsyncSGDWorker(conf, mesh=mesh8)
        assert worker.num_slots == 1002
        assert worker.directory.num_slots == 1001
        b = random_sparse(256, 512, 8, seed=0, binary=True)
        prepped = worker.prep(b, device_put=False)
        assert isinstance(prepped, ELLBitsBatch)
        import jax.numpy as jnp

        bits = slot_bits(worker.num_slots)
        want = worker.directory.slots(b.indices)
        got = []
        for d in range(prepped.counts.shape[0]):
            nsub = int(prepped.counts[d])
            dec = np.asarray(
                unpack_bits(
                    jnp.asarray(prepped.slots_words[d]), prepped.rows * 8, bits
                )
            )[: nsub * 8]
            got.append(dec)
        np.testing.assert_array_equal(np.concatenate(got), want)


class TestAddNoisePushFilter:
    """ADD_NOISE (ref src/filter/add_noise.h) applied device-side to each
    worker's gradient contribution inside the fused step."""

    def _train(self, mesh8, w_true, push_filter):
        conf = make_conf(num_slots=2048)
        conf.async_sgd.push_filter = push_filter
        worker = AsyncSGDWorker(conf, mesh=mesh8)
        for b in synth(5, w_true):
            worker.collect(worker.process_minibatch(b))
        return worker.weights_dense()

    def test_noise_perturbs_and_replays_deterministically(self, mesh8, w_true):
        clean = self._train(mesh8, w_true, [])
        noisy1 = self._train(
            mesh8, w_true, [{"type": "add_noise", "std": 0.05}]
        )
        noisy2 = self._train(
            mesh8, w_true, [{"type": "add_noise", "std": 0.05}]
        )
        assert not np.allclose(noisy1, clean, atol=1e-6)
        np.testing.assert_allclose(noisy1, noisy2, atol=0)  # seeded replay
        # zero std is the identity
        zero = self._train(mesh8, w_true, [{"type": "add_noise", "std": 0.0}])
        np.testing.assert_allclose(zero, clean, atol=0)

    def test_noise_still_converges(self, mesh8, w_true):
        conf = make_conf(num_slots=4096)
        conf.async_sgd.push_filter = [{"type": "add_noise", "std": 0.02}]
        worker = AsyncSGDWorker(conf, mesh=mesh8)
        worker.train(synth(40, w_true))
        ev = worker.evaluate(random_sparse(2000, 512, 8, seed=999, w_true=w_true))
        assert ev["auc"] > 0.6

    def test_composes_with_quantized_push(self, mesh8, w_true):
        conf = make_conf(num_slots=2048)
        conf.async_sgd.push_filter = [
            {"type": "add_noise", "std": 0.05},
            {"type": "fixing_float", "num_bytes": 2},
        ]
        worker = AsyncSGDWorker(conf, mesh=mesh8)
        for b in synth(5, w_true):
            worker.collect(worker.process_minibatch(b))
        assert np.isfinite(worker.weights_dense()).all()

    def test_mean_only_noise_applies(self, mesh8, w_true):
        clean = self._train(mesh8, w_true, [])
        shifted = self._train(
            mesh8, w_true, [{"type": "add_noise", "mean": 0.1}]
        )
        assert not np.allclose(shifted, clean, atol=1e-6)

    def test_pull_wire_noise(self, mesh8, w_true):
        conf = make_conf(num_slots=2048)
        conf.async_sgd.pull_filter = [{"type": "add_noise", "std": 0.05}]
        worker = AsyncSGDWorker(conf, mesh=mesh8)
        for b in synth(5, w_true):
            worker.collect(worker.process_minibatch(b))
        noisy = worker.weights_dense()
        clean = self._train(mesh8, w_true, [])
        assert not np.allclose(noisy, clean, atol=1e-6)
        assert np.isfinite(noisy).all()

    def test_train_with_steps_per_launch_matches_sequential(self, mesh8, w_true):
        def run(T):
            conf = make_conf(num_slots=2048)
            conf.async_sgd.ell_lanes = 8
            conf.async_sgd.wire = "bits"
            conf.async_sgd.steps_per_launch = T
            worker = AsyncSGDWorker(conf, mesh=mesh8)
            worker.train(
                random_sparse(256, 512, 8, seed=100 + i, w_true=w_true,
                              binary=True)
                for i in range(7)  # 7 = 2 full groups of 3 + a tail of 1
            )
            return worker

        seq, fused = run(1), run(3)
        np.testing.assert_allclose(
            fused.weights_dense(), seq.weights_dense(), atol=1e-6
        )
        assert (
            fused.progress.num_examples_processed
            == seq.progress.num_examples_processed
            == 7 * 256
        )

    def test_train_steps_per_launch_falls_back_on_ragged_batches(
        self, mesh8, w_true
    ):
        """Non-bits-eligible batches (valued features) must run
        per-minibatch rather than raise (the CLI path with libsvm data)."""
        conf = make_conf(num_slots=2048)
        conf.async_sgd.ell_lanes = 8
        conf.async_sgd.wire = "bits"
        conf.async_sgd.steps_per_launch = 3
        worker = AsyncSGDWorker(conf, mesh=mesh8)
        worker.train(synth(5, w_true))  # valued features -> fallback
        assert worker.progress.num_examples_processed == 5 * 256


class TestNarrowPullGather:
    """pull_gather="narrow": gather quantized CODES + zero-mask,
    dequantize post-gather — the reference's production pull config
    (1-byte FIXING_FLOAT, example/linear/ctr/online_l1lr.conf). The
    formulation must be EXACTLY the wide path's math: dequantize is
    elementwise with per-shard scalar scales, so
    dequantize(gather(q)) == gather(dequantize(q)) bit-for-bit."""

    def _train(self, w_true, gather_mode, wire="bits", pull_bytes=1):
        conf = make_conf(num_slots=4096)
        conf.async_sgd.ell_lanes = 8
        conf.async_sgd.wire = wire
        conf.async_sgd.pull_gather = gather_mode
        conf.async_sgd.pull_filter = [
            {"type": "fixing_float", "num_bytes": pull_bytes}
        ]
        mesh = Postoffice.instance().start().mesh
        worker = AsyncSGDWorker(conf, mesh=mesh)
        worker.train(synth_binary(6, w_true))
        return worker.weights_dense()

    @pytest.mark.parametrize("wire", ["bits", "i32"])
    def test_narrow_equals_wide_bitwise(self, mesh8, w_true, wire):
        w_n = self._train(w_true, "narrow", wire=wire)
        Postoffice.reset()
        w_w = self._train(w_true, "wide", wire=wire)
        np.testing.assert_array_equal(w_n, w_w)
        assert np.abs(w_n).max() > 0  # training actually moved weights

    def test_auto_wide_and_forced_narrow_agree(self, mesh8, w_true):
        # auto resolves to wide (measured faster on TPU); the knob
        # still forces narrow, and it stays exact
        w_n = self._train(w_true, "narrow", pull_bytes=2)
        Postoffice.reset()
        w_a = self._train(w_true, "auto", pull_bytes=2)
        Postoffice.reset()
        w_w = self._train(w_true, "wide", pull_bytes=2)
        np.testing.assert_array_equal(w_n, w_w)
        np.testing.assert_array_equal(w_a, w_w)

    def test_bad_pull_gather_rejected(self, mesh8):
        conf = make_conf()
        conf.async_sgd.pull_gather = "sideways"
        with pytest.raises(ValueError, match="pull_gather"):
            AsyncSGDWorker(conf, mesh=mesh8)

    def test_conf_parses_pull_gather(self):
        conf = parse_conf(
            'training_data { format: "libsvm" file: "x" }\n'
            'async_sgd { pull_gather: "narrow" }\n'
        )
        assert conf.async_sgd.pull_gather == "narrow"

    def test_auto_selects_wide_at_every_width(self):
        """Direct selection assertion: the equality tests above cannot
        observe WHICH path auto picked (narrow and wide are bitwise
        identical by design). Auto resolves to WIDE for every pull
        width — the on-chip A/B measured narrow LOSING on TPU
        (row-granularity-bound gathers: u8+mask 23.6 ms vs f32
        18.0 ms; bench _q1 585k vs 632k ex/s, BENCH_ONCHIP 08-02) —
        while the explicit knob still forces narrow for parts where
        bytes bind."""
        from parameter_server_tpu.apps.linear.async_sgd import (
            make_pull_lookup,
        )

        class U:
            weights = staticmethod(lambda p: p)

        for quant in (1, 2, 0):
            _, lookup = make_pull_lookup(U(), quant)
            assert lookup.__name__ == "wide_lookup", (
                quant, lookup.__name__)
        _, forced = make_pull_lookup(U(), 1, narrow=True)
        assert forced.__name__ == "narrow_lookup"


class TestPipelinedTrain:
    """train(pipelined=True): prep/stack/upload on a daemon thread,
    ordered submits on the training thread — trajectories must be
    BIT-identical to the unpipelined path (same submission order ⇒
    same seeds and snapshot schedule)."""

    def _run(self, w_true, pipelined, T=4, wire="bits", delay=2):
        conf = make_conf(num_slots=2048, max_delay=delay)
        conf.async_sgd.ell_lanes = 8
        conf.async_sgd.wire = wire
        conf.async_sgd.steps_per_launch = T
        mesh = Postoffice.instance().start().mesh
        worker = AsyncSGDWorker(conf, mesh=mesh)
        prog = worker.train(synth_binary(9, w_true), pipelined=pipelined)
        return worker.weights_dense(), prog

    def test_bitwise_equal_supersteps(self, mesh8, w_true):
        w_p, prog_p = self._run(w_true, True)
        Postoffice.reset()
        w_s, prog_s = self._run(w_true, False)
        np.testing.assert_array_equal(w_p, w_s)
        assert (
            prog_p.num_examples_processed == prog_s.num_examples_processed
        )
        np.testing.assert_allclose(prog_p.objective, prog_s.objective)
        assert np.abs(w_p).max() > 0

    def test_bitwise_equal_fallback_path(self, mesh8, w_true):
        # valued batches are not bits-wire eligible: the pipeline must
        # take the per-minibatch fallback and still match exactly
        def run(pipelined):
            conf = make_conf(num_slots=2048, max_delay=1)
            conf.async_sgd.steps_per_launch = 3
            mesh = Postoffice.instance().start().mesh
            worker = AsyncSGDWorker(conf, mesh=mesh)
            worker.train(synth(6, w_true), pipelined=pipelined)
            return worker.weights_dense()

        w_p = run(True)
        Postoffice.reset()
        w_s = run(False)
        np.testing.assert_array_equal(w_p, w_s)

    def test_producer_exception_reaches_caller(self, mesh8, w_true):
        conf = make_conf(num_slots=2048)
        conf.async_sgd.steps_per_launch = 2
        conf.async_sgd.ell_lanes = 8
        conf.async_sgd.wire = "bits"
        worker = AsyncSGDWorker(conf, mesh=mesh8)

        def poisoned():
            yield from synth_binary(2, w_true)
            raise RuntimeError("reader died")

        with pytest.raises(RuntimeError, match="reader died"):
            worker.train(poisoned(), pipelined=True)
