"""The per-component perf suite must stay runnable (ref src/test/
*_perf_ps.cc built under the same make target as the unit tests)."""

import json
import subprocess
import sys


def test_benchmarks_smoke_all(capsys):
    from parameter_server_tpu.benchmarks import REGISTRY
    from parameter_server_tpu.benchmarks import components  # noqa: F401
    from parameter_server_tpu.system.postoffice import Postoffice

    assert set(REGISTRY) == {
        "kv_vector", "kv_map", "kv_layer", "network", "sparse_matrix",
        "attention", "step_phases", "executor", "host_ingest", "wire",
        "stream_prep", "serve", "decode_batching", "trace",
        "ftrl_sparse_ab", "ftrl_chain", "recovery_drill", "roofline",
        "bundle", "learning", "history_ab", "rebalance", "consistency",
    }
    for name, fn in sorted(REGISTRY.items()):
        fn(True)
    Postoffice.reset()
    lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    metrics = [json.loads(l) for l in lines]
    assert len(metrics) >= 10
    for m in metrics:
        assert m["value"] > 0, m
        assert {"metric", "value", "unit"} <= set(m)


def test_benchmarks_cli_rejects_unknown():
    proc = subprocess.run(
        [sys.executable, "-m", "parameter_server_tpu.benchmarks", "nope"],
        capture_output=True,
        text=True,
    )
    assert proc.returncode != 0
    assert "unknown benchmark" in proc.stderr
