"""Darlin (delayed block proximal gradient) tests: block-update parity vs a
NumPy transcription of the reference's ComputeGradient/UpdateWeight/
UpdateDual math, plus convergence/KKT-filter behavior."""

import numpy as np
import pytest

from parameter_server_tpu.apps.linear.config import (
    BCDConfig,
    Config,
    LearningRateConfig,
    LossConfig,
    PenaltyConfig,
)
from parameter_server_tpu.apps.linear.darlin import DarlinScheduler, DarlinSolver
from parameter_server_tpu.learner.bcd import BCDScheduler, FeatureBlock
from parameter_server_tpu.system.postoffice import Postoffice
from parameter_server_tpu.utils import evaluation
from parameter_server_tpu.utils.range import Range
from parameter_server_tpu.utils.sparse import random_sparse


@pytest.fixture(autouse=True)
def fresh_po():
    Postoffice.reset()
    yield
    Postoffice.reset()


def make_conf(lam=1.0, passes=10, ratio=4.0):
    conf = Config()
    conf.loss = LossConfig(type="logit")
    conf.penalty = PenaltyConfig(type="l1", lambda_=[lam])
    conf.learning_rate = LearningRateConfig(alpha=1.0)
    conf.darlin = BCDConfig(
        num_data_pass=passes, feature_block_ratio=ratio, epsilon=1e-6
    )
    return conf


@pytest.fixture(scope="module")
def dataset():
    rng = np.random.default_rng(0)
    w_true = (rng.normal(size=200) * (rng.random(200) < 0.15) * 2).astype(np.float32)
    return random_sparse(2000, 200, 10, seed=1, w_true=w_true), w_true


def darlin_block_oracle(X, y, w, delta, active, dual, lam, eta, delta_max, thr):
    """NumPy transcription of darlin.h ComputeGradient (417-462) +
    UpdateWeight (261-306) + UpdateDual (558-588) for a whole-feature block."""
    n, f = X.shape
    tau = 1.0 / (1.0 + dual)
    G = X.T @ (-y * tau)
    U = np.zeros(f)
    for j in range(f):
        xj = X[:, j]
        U[j] = np.sum(
            np.minimum(tau * (1 - tau) * np.exp(np.abs(xj) * delta[j]), 0.25) * xj * xj
        )
    u = U / eta + 1e-10
    g_pos, g_neg = G + lam, G - lam
    new_w, new_delta, new_active = w.copy(), delta.copy(), active.copy()
    violation = 0.0
    d_w = np.zeros(f)
    for j in range(f):
        if not active[j]:
            continue
        if w[j] == 0:
            vio = 0.0
            if g_pos[j] < 0:
                vio = -g_pos[j]
            elif g_neg[j] > 0:
                vio = g_neg[j]
            elif g_pos[j] > thr and g_neg[j] < -thr:
                new_active[j] = False
                continue
            violation = max(violation, vio)
        d = -w[j]
        if g_pos[j] <= u[j] * w[j]:
            d = -g_pos[j] / u[j]
        elif g_neg[j] >= u[j] * w[j]:
            d = -g_neg[j] / u[j]
        d = min(delta[j], max(-delta[j], d))
        d_w[j] = d
        new_delta[j] = min(delta_max, 2 * abs(d) + 0.1)
        new_w[j] = w[j] + d
    new_dual = dual * np.exp(y * (X @ d_w))
    return new_w, new_delta, new_active, new_dual, violation


class TestBlockParity:
    def test_single_block_matches_oracle(self, mesh8):
        # duplicate-free batch (the U term is nonlinear per entry, so dup
        # (row, col) pairs would differ from the dense-merged oracle)
        from parameter_server_tpu.utils.sparse import from_dense

        rng = np.random.default_rng(3)
        dense = (rng.random((400, 120)) < 0.08) * rng.normal(size=(400, 120))
        w_true = (rng.normal(size=120) * (rng.random(120) < 0.2) * 2).astype(np.float32)
        logits = dense @ w_true
        y = np.where(rng.random(400) < 1 / (1 + np.exp(-logits)), 1.0, -1.0)
        data = from_dense(dense.astype(np.float32), y.astype(np.float32))
        conf = make_conf(lam=0.5, ratio=0)  # one block = all features
        sched = BCDScheduler(conf.darlin)
        localized = sched.set_data(data)
        blocks = [FeatureBlock(0, Range(0, localized.cols))]
        solver = DarlinSolver(conf, mesh=mesh8)
        solver.init_data(localized, blocks)

        X = localized.to_dense()
        w0 = solver.w.copy()
        delta0 = solver.delta.copy()
        active0 = solver.active.copy()
        dual0 = np.ones(localized.n)

        vio = solver.update_block(0, blocks, thr=1e20, reset=False)
        ew, edelta, eactive, edual, evio = darlin_block_oracle(
            X, localized.y.astype(np.float64), w0, delta0, active0, dual0,
            lam=0.5, eta=1.0, delta_max=conf.darlin.delta_max_value, thr=1e20,
        )
        np.testing.assert_allclose(solver.w, ew, atol=1e-4)
        np.testing.assert_allclose(solver.delta, edelta, atol=1e-4)
        np.testing.assert_array_equal(solver.active, eactive)
        dual = np.asarray(solver.dual).ravel()[: localized.n]
        np.testing.assert_allclose(dual, edual, rtol=1e-3)
        assert abs(vio - evio) < 1e-3


class TestConvergence:
    def test_objective_decreases_and_learns(self, mesh8, dataset):
        data, _ = dataset
        sched = DarlinScheduler(make_conf(passes=10), mesh=mesh8)
        prog = sched.run_on(data)
        objs = [sched.g_progress[i].objective for i in sorted(sched.g_progress)]
        assert all(b <= a + 1e-6 for a, b in zip(objs, objs[1:]))
        auc = evaluation.auc(data.y, sched.solver.predict_margin())
        assert auc > 0.8

    def test_kkt_filter_prunes_active_set(self, mesh8, dataset):
        data, _ = dataset
        sched = DarlinScheduler(make_conf(passes=6), mesh=mesh8)
        prog = sched.run_on(data)
        assert prog.nnz_active_set < sched.data.cols  # some coords suspended

    def test_heavier_l1_sparser(self, mesh8, dataset):
        data, _ = dataset
        nnz = []
        for lam in (0.1, 10.0):
            Postoffice.reset()
            sched = DarlinScheduler(make_conf(lam=lam, passes=6), mesh=mesh8)
            nnz.append(sched.run_on(data).nnz_w)
        assert nnz[1] < nnz[0] * 0.7

    def test_save_model(self, mesh8, dataset, tmp_path):
        data, _ = dataset
        sched = DarlinScheduler(make_conf(passes=4), mesh=mesh8)
        prog = sched.run_on(data)
        path = tmp_path / "darlin.txt"
        files = sched.save_model(str(path))
        assert files and all(f.startswith(str(path) + "_S") for f in files)
        lines = [l for f in files for l in open(f).read().strip().splitlines()]
        assert len(lines) == prog.nnz_w


class TestBCDFramework:
    def test_divide_feature_blocks(self, mesh8, dataset):
        data, _ = dataset
        sched = BCDScheduler(BCDConfig(feature_block_ratio=3.0))
        sched.set_data(data)
        blocks = sched.divide_feature_blocks(num_groups=2)
        assert len(blocks) == 6
        total = sum(b.col_range.size() for b in blocks)
        assert total == sched.data.cols

    def test_progress_merge(self):
        from parameter_server_tpu.learner.bcd import BCDProgress

        a = BCDProgress(objective=1.0, violation=0.5, nnz_w=10)
        a.merge(BCDProgress(objective=2.0, violation=0.3, nnz_w=5))
        assert a.objective == 3.0 and a.violation == 0.5 and a.nnz_w == 15
