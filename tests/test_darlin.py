"""Darlin (delayed block proximal gradient) tests: block-update parity vs a
NumPy transcription of the reference's ComputeGradient/UpdateWeight/
UpdateDual math, plus convergence/KKT-filter behavior."""

import numpy as np
import pytest

from parameter_server_tpu.apps.linear.config import (
    BCDConfig,
    Config,
    LearningRateConfig,
    LossConfig,
    PenaltyConfig,
)
from parameter_server_tpu.apps.linear.darlin import DarlinScheduler, DarlinSolver
from parameter_server_tpu.learner.bcd import BCDScheduler, FeatureBlock
from parameter_server_tpu.system.postoffice import Postoffice
from parameter_server_tpu.utils import evaluation
from parameter_server_tpu.utils.range import Range
from parameter_server_tpu.utils.sparse import random_sparse


@pytest.fixture(autouse=True)
def fresh_po():
    Postoffice.reset()
    yield
    Postoffice.reset()


def make_conf(lam=1.0, passes=10, ratio=4.0):
    conf = Config()
    conf.loss = LossConfig(type="logit")
    conf.penalty = PenaltyConfig(type="l1", lambda_=[lam])
    conf.learning_rate = LearningRateConfig(alpha=1.0)
    conf.darlin = BCDConfig(
        num_data_pass=passes, feature_block_ratio=ratio, epsilon=1e-6
    )
    return conf


@pytest.fixture(scope="module")
def dataset():
    rng = np.random.default_rng(0)
    w_true = (rng.normal(size=200) * (rng.random(200) < 0.15) * 2).astype(np.float32)
    return random_sparse(2000, 200, 10, seed=1, w_true=w_true), w_true


def darlin_block_oracle(X, y, w, delta, active, dual, lam, eta, delta_max, thr):
    """NumPy transcription of darlin.h ComputeGradient (417-462) +
    UpdateWeight (261-306) + UpdateDual (558-588) for a whole-feature block."""
    n, f = X.shape
    tau = 1.0 / (1.0 + dual)
    G = X.T @ (-y * tau)
    U = np.zeros(f)
    for j in range(f):
        xj = X[:, j]
        U[j] = np.sum(
            np.minimum(tau * (1 - tau) * np.exp(np.abs(xj) * delta[j]), 0.25) * xj * xj
        )
    u = U / eta + 1e-10
    g_pos, g_neg = G + lam, G - lam
    new_w, new_delta, new_active = w.copy(), delta.copy(), active.copy()
    violation = 0.0
    d_w = np.zeros(f)
    for j in range(f):
        if not active[j]:
            continue
        if w[j] == 0:
            vio = 0.0
            if g_pos[j] < 0:
                vio = -g_pos[j]
            elif g_neg[j] > 0:
                vio = g_neg[j]
            elif g_pos[j] > thr and g_neg[j] < -thr:
                new_active[j] = False
                continue
            violation = max(violation, vio)
        d = -w[j]
        if g_pos[j] <= u[j] * w[j]:
            d = -g_pos[j] / u[j]
        elif g_neg[j] >= u[j] * w[j]:
            d = -g_neg[j] / u[j]
        d = min(delta[j], max(-delta[j], d))
        d_w[j] = d
        new_delta[j] = min(delta_max, 2 * abs(d) + 0.1)
        new_w[j] = w[j] + d
    new_dual = dual * np.exp(y * (X @ d_w))
    return new_w, new_delta, new_active, new_dual, violation


class TestBlockParity:
    def test_single_block_matches_oracle(self, mesh8):
        # duplicate-free batch (the U term is nonlinear per entry, so dup
        # (row, col) pairs would differ from the dense-merged oracle)
        from parameter_server_tpu.utils.sparse import from_dense

        rng = np.random.default_rng(3)
        dense = (rng.random((400, 120)) < 0.08) * rng.normal(size=(400, 120))
        w_true = (rng.normal(size=120) * (rng.random(120) < 0.2) * 2).astype(np.float32)
        logits = dense @ w_true
        y = np.where(rng.random(400) < 1 / (1 + np.exp(-logits)), 1.0, -1.0)
        data = from_dense(dense.astype(np.float32), y.astype(np.float32))
        conf = make_conf(lam=0.5, ratio=0)  # one block = all features
        sched = BCDScheduler(conf.darlin)
        localized = sched.set_data(data)
        blocks = [FeatureBlock(0, Range(0, localized.cols))]
        solver = DarlinSolver(conf, mesh=mesh8)
        solver.init_data(localized, blocks)

        X = localized.to_dense()
        w0 = solver.w.copy()
        delta0 = solver.delta.copy()
        active0 = solver.active.copy()
        dual0 = np.ones(localized.n)

        vio = solver.update_block(0, blocks, thr=1e20, reset=False)
        ew, edelta, eactive, edual, evio = darlin_block_oracle(
            X, localized.y.astype(np.float64), w0, delta0, active0, dual0,
            lam=0.5, eta=1.0, delta_max=conf.darlin.delta_max_value, thr=1e20,
        )
        np.testing.assert_allclose(solver.w, ew, atol=1e-4)
        np.testing.assert_allclose(solver.delta, edelta, atol=1e-4)
        np.testing.assert_array_equal(solver.active, eactive)
        dual = np.asarray(solver.dual).ravel()[: localized.n]
        np.testing.assert_allclose(dual, edual, rtol=1e-3)
        assert abs(vio - evio) < 1e-3


class TestConvergence:
    def test_objective_decreases_and_learns(self, mesh8, dataset):
        data, _ = dataset
        sched = DarlinScheduler(make_conf(passes=10), mesh=mesh8)
        prog = sched.run_on(data)
        objs = [sched.g_progress[i].objective for i in sorted(sched.g_progress)]
        assert all(b <= a + 1e-6 for a, b in zip(objs, objs[1:]))
        auc = evaluation.auc(data.y, sched.solver.predict_margin())
        assert auc > 0.8

    def test_kkt_filter_prunes_active_set(self, mesh8, dataset):
        data, _ = dataset
        sched = DarlinScheduler(make_conf(passes=6), mesh=mesh8)
        prog = sched.run_on(data)
        assert prog.nnz_active_set < sched.data.cols  # some coords suspended

    def test_heavier_l1_sparser(self, mesh8, dataset):
        data, _ = dataset
        nnz = []
        for lam in (0.1, 10.0):
            Postoffice.reset()
            sched = DarlinScheduler(make_conf(lam=lam, passes=6), mesh=mesh8)
            nnz.append(sched.run_on(data).nnz_w)
        assert nnz[1] < nnz[0] * 0.7

    def test_save_model(self, mesh8, dataset, tmp_path):
        data, _ = dataset
        sched = DarlinScheduler(make_conf(passes=4), mesh=mesh8)
        prog = sched.run_on(data)
        path = tmp_path / "darlin.txt"
        files = sched.save_model(str(path))
        assert files and all(f.startswith(str(path) + "_S") for f in files)
        lines = [l for f in files for l in open(f).read().strip().splitlines()]
        assert len(lines) == prog.nnz_w


class TestTauPipelining:
    """ref darlin.h AddWaitTime / Submit(wait ≤ τ): with max_block_delay=τ,
    up to τ+1 block steps must be in flight simultaneously."""

    def test_blocks_pipeline_with_tau(self, mesh8, dataset):
        data, _ = dataset
        conf = make_conf(passes=3, ratio=8.0)
        conf.darlin.max_block_delay = 2
        sched = DarlinScheduler(conf, mesh=mesh8)
        prog = sched.run_on(data)
        assert len(sched.fea_blk) >= 4, "need several blocks to pipeline"
        assert sched.max_dispatch_window >= 2
        objs = [sched.g_progress[i].objective for i in sorted(sched.g_progress)]
        assert objs[-1] < objs[0]  # still converges with delayed blocks

    def test_tau_zero_serializes(self, mesh8, dataset):
        data, _ = dataset
        conf = make_conf(passes=2, ratio=8.0)
        conf.darlin.max_block_delay = 0
        sched = DarlinScheduler(conf, mesh=mesh8)
        sched.run_on(data)
        assert sched.max_dispatch_window <= 1

    def test_tau_matches_serial_result(self, mesh8, dataset):
        # block steps chain through the dual on device, so τ>0 pipelining
        # must be numerically identical to the serial schedule
        data, _ = dataset
        runs = []
        for tau in (0, 3):
            Postoffice.reset()
            conf = make_conf(passes=3, ratio=4.0)
            conf.darlin.max_block_delay = tau
            conf.darlin.random_feature_block_order = False
            sched = DarlinScheduler(conf, mesh=mesh8)
            sched.run_on(data)
            runs.append(sched.solver.w)
        np.testing.assert_allclose(runs[0], runs[1], atol=1e-6)


class TestCriteoEndToEnd:
    """VERDICT r1 #1 done-criterion: darlin end-to-end on criteo text via
    SlotReader with per-slot feature blocks."""

    def _write_criteo(self, tmp_path, n=300):
        path = tmp_path / "train.criteo"
        rng = np.random.default_rng(7)
        with open(path, "w") as f:
            for _ in range(n):
                ints = "\t".join(str(rng.integers(0, 8)) for _ in range(13))
                cats = "\t".join(
                    f"tok{rng.integers(0, 30):04d}" for _ in range(26)
                )
                label = int(rng.integers(0, 2))
                f.write(f"{label}\t{ints}\t{cats}\n")
        return str(path)

    def test_darlin_on_criteo_slots(self, mesh8, tmp_path):
        path = self._write_criteo(tmp_path)
        conf = make_conf(lam=0.1, passes=4, ratio=0.5)
        sched = DarlinScheduler(conf, mesh=mesh8)
        sched.load_data([path], "criteo", cache_dir=str(tmp_path / "cache"))
        # slot-major layout: 39 feature groups, contiguous column ranges
        assert len(sched.slot_ranges) == 39
        blocks = sched.divide_feature_blocks()
        assert {b.group for b in blocks} == set(range(1, 40))
        prog = sched.run_loaded()
        objs = [sched.g_progress[i].objective for i in sorted(sched.g_progress)]
        assert objs[-1] < objs[0]
        # per-slot blocks partition the whole column space
        total = sum(b.col_range.size() for b in sched.fea_blk)
        assert total == sched.data.cols


class TestSlotEdgeCases:
    def test_group_zero_features_train(self, mesh8, tmp_path):
        # terafea keys below 2^54 land in group 0; they must still be
        # covered by a feature block (our labels never live in slots)
        rng = np.random.default_rng(5)
        path = tmp_path / "t.terafea"
        with open(path, "w") as f:
            for i in range(200):
                k0 = rng.integers(0, 50)          # group 0
                k1 = (1 << 54) | rng.integers(0, 50)  # group 1
                f.write(f"{i % 2 * 2 - 1} {i} | {k0} {k1}\n")
        conf = make_conf(lam=0.05, passes=3, ratio=0)
        sched = DarlinScheduler(conf, mesh=mesh8)
        sched.load_data([str(path)], "terafea")
        blocks = sched.divide_feature_blocks()
        assert 0 in {b.group for b in blocks}
        total = sum(b.col_range.size() for b in blocks)
        assert total == sched.data.cols  # every column owned by a block

    def test_reload_resets_slot_layout(self, mesh8, dataset, tmp_path):
        # criteo load populates slot_ranges; a later synthetic batch (no
        # slot ids) must not inherit them
        t = TestCriteoEndToEnd()
        path = t._write_criteo(tmp_path, n=50)
        data, _ = dataset
        sched = DarlinScheduler(make_conf(passes=2), mesh=mesh8)
        sched.load_data([path], "criteo", cache_dir=str(tmp_path / "c"))
        assert sched.slot_ranges
        sched.set_data(data)  # synthetic, slot-free
        assert not sched.slot_ranges and sched.info is None
        blocks = sched.divide_feature_blocks()
        total = sum(b.col_range.size() for b in blocks)
        assert total == sched.data.cols


class TestBCDFramework:
    def test_divide_feature_blocks(self, mesh8, dataset):
        data, _ = dataset
        sched = BCDScheduler(BCDConfig(feature_block_ratio=3.0))
        sched.set_data(data)
        blocks = sched.divide_feature_blocks(num_groups=2)
        assert len(blocks) == 6
        total = sum(b.col_range.size() for b in blocks)
        assert total == sched.data.cols

    def test_progress_merge(self):
        from parameter_server_tpu.learner.bcd import BCDProgress

        a = BCDProgress(objective=1.0, violation=0.5, nnz_w=10)
        a.merge(BCDProgress(objective=2.0, violation=0.3, nnz_w=5))
        assert a.objective == 3.0 and a.violation == 0.5 and a.nnz_w == 15
