"""bench regression sentinel (script/bench_diff.py, `make bench-diff`).

Fixture records in tests/data/bench_diff/ model the real trajectory's
shapes: driver-wrapped rounds (``{"parsed": ...}``), an outage round,
a pre-protocol artifact record (the retracted r01 5.25M dispatch-rate
number), and judged records in the raw shape. The sentinel must flag a
seeded 30% throughput regression, pass an in-band record, skip
non-measurements — and pass the repo's real committed trajectory.
"""

from __future__ import annotations

import importlib.util
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(os.path.dirname(__file__), "data", "bench_diff")

_spec = importlib.util.spec_from_file_location(
    "_bench_diff", os.path.join(REPO, "script", "bench_diff.py")
)
bench_diff = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(bench_diff)


def fx(name: str) -> str:
    return os.path.join(FIXTURES, name)


TRAJ = [
    fx("traj_r0_artifact.json"),
    fx("traj_r1.json"),
    fx("traj_r2_outage.json"),
    fx("traj_r3.json"),
    fx("traj_r4.json"),
]


def run_cli(*argv):
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "script", "bench_diff.py"), *argv],
        capture_output=True,
        text=True,
        timeout=60,
    )
    lines = [
        json.loads(l) for l in proc.stdout.splitlines() if l.strip()
    ]
    return proc.returncode, lines


class TestRecordLoading:
    def test_unwraps_driver_shape_and_skips_failures(self):
        rec = bench_diff.load_record(fx("traj_r1.json"))
        assert rec["value"] == 1_280_000.0
        assert bench_diff.is_valid(rec)
        assert not bench_diff.is_valid(
            bench_diff.load_record(fx("traj_r2_outage.json"))
        )

    def test_pre_protocol_artifact_is_not_a_baseline(self):
        """The retracted round-1 5.25M dispatch-rate artifact (bench.py
        round-2 MEASUREMENT NOTE) must never seed the baseline — the
        schema gate is the flushed-protocol fields."""
        rec = bench_diff.load_record(fx("traj_r0_artifact.json"))
        assert rec["value"] > 5e6  # it LOOKS like a great baseline...
        assert not bench_diff.is_valid(rec)  # ...and is rejected

    def test_raw_record_shape_loads_too(self):
        assert bench_diff.is_valid(bench_diff.load_record(fx("new_ok.json")))


class TestDiffMath:
    def _priors(self):
        return [
            bench_diff.load_record(fx(n))
            for n in ("traj_r1.json", "traj_r3.json", "traj_r4.json")
        ]

    def test_seeded_30pct_regression_flagged(self):
        new = bench_diff.load_record(fx("new_regressed.json"))
        rows, regressed = bench_diff.diff(new, self._priors())
        assert regressed
        by_metric = {r["metric"]: r for r in rows}
        assert by_metric["e2e_median_window"]["status"] == "REGRESSION"
        assert by_metric["e2e_median_window"]["ratio"] == pytest.approx(
            238_000.0 / 341_000.0, abs=0.01
        )
        # the device-only headline is in band — per-metric verdicts
        assert by_metric["value"]["status"] == "ok"

    def test_in_band_record_passes(self):
        new = bench_diff.load_record(fx("new_ok.json"))
        rows, regressed = bench_diff.diff(new, self._priors())
        assert not regressed
        assert all(r["status"] == "ok" for r in rows)

    def test_baseline_is_median_of_priors(self):
        new = bench_diff.load_record(fx("new_ok.json"))
        rows, _ = bench_diff.diff(new, self._priors())
        by_metric = {r["metric"]: r for r in rows}
        assert by_metric["value"]["baseline_median"] == 1_310_000.0

    def test_band_widens_with_trajectory_noise_but_is_capped(self):
        assert bench_diff.band_for([100.0, 100.0, 100.0], 0.2, 0.45) == 0.2
        # a 25%-noisy trajectory earns a wider band (1.5 * max dev)...
        assert bench_diff.band_for([100.0, 75.0, 104.0], 0.2, 0.45) == (
            pytest.approx(0.375, abs=0.01)
        )
        # ...but can never alibi arbitrary regressions
        assert bench_diff.band_for([100.0, 20.0], 0.2, 0.45) == 0.45

    def test_improvement_never_flags(self):
        new = dict(bench_diff.load_record(fx("new_ok.json")))
        new["value"] = 5_000_000.0
        rows, regressed = bench_diff.diff(new, self._priors())
        assert not regressed
        assert {r["metric"]: r for r in rows}["value"]["status"] == "improved"

    def test_no_priors_means_no_baseline_pass(self):
        new = bench_diff.load_record(fx("new_ok.json"))
        rows, regressed = bench_diff.diff(new, [])
        assert not regressed
        assert all(r["status"] == "no-baseline" for r in rows)

    def test_recovery_section_is_metadata_never_banded(self):
        """The chaos-plane `recovery` section carries drill wall times
        (MTTR, detection) and degraded/shed counts — host-dependent
        metadata, not throughput the sentinel may band. A catastrophic-
        looking recovery section must not flag, and WATCHED is
        statically barred from pointing into any metadata section."""
        assert "recovery" in bench_diff.METADATA_SECTIONS
        assert not (
            {k for k, _ in bench_diff.WATCHED} & bench_diff.METADATA_SECTIONS
        )
        new = dict(bench_diff.load_record(fx("new_ok.json")))
        new["recovery"] = {  # 100x-worse drill numbers, all ignored
            "mttr_ms": 1e9, "detection_ms": 1e9,
            "serve": {"degraded_served": 1e9, "failed": 1e9},
        }
        priors = self._priors()
        rows, regressed = bench_diff.diff(new, priors)
        assert not regressed
        reported = {r["metric"] for r in rows}
        assert reported  # the scalar metrics are still judged
        assert not reported & bench_diff.METADATA_SECTIONS

    def test_decode_batching_section_is_metadata_never_banded(self):
        """The continuous-batching `decode_batching` section quotes its
        own paired-rep medians (batched vs sequential tokens/s under
        churn) with the on-chip target stated in-record — a
        self-disclosing A/B whose host-dependent wall clocks the
        sentinel must never band."""
        assert "decode_batching" in bench_diff.METADATA_SECTIONS
        assert not (
            {k for k, _ in bench_diff.WATCHED} & bench_diff.METADATA_SECTIONS
        )
        new = dict(bench_diff.load_record(fx("new_ok.json")))
        new["decode_batching"] = {  # catastrophic A/B, all ignored
            "speedup_at_8": 0.01,
            "arms": [{"slots": 8, "batched_tokens_per_sec": 1.0}],
            "device_replica": {"degraded_served": 1e9},
        }
        rows, regressed = bench_diff.diff(new, self._priors())
        assert not regressed
        reported = {r["metric"] for r in rows}
        assert reported
        assert not reported & bench_diff.METADATA_SECTIONS

    def test_consistency_section_is_metadata_never_banded(self):
        """The self-driving consistency `consistency` section quotes
        its own paired-rep A/B medians (τ arms with an emulated pull
        RTT, KKT filter off/on key reductions) plus the divergence
        drill episode — self-disclosing run metadata whose
        host-dependent wall clocks the sentinel must never band."""
        assert "consistency" in bench_diff.METADATA_SECTIONS
        assert not (
            {k for k, _ in bench_diff.WATCHED} & bench_diff.METADATA_SECTIONS
        )
        new = dict(bench_diff.load_record(fx("new_ok.json")))
        new["consistency"] = {  # catastrophic frontier, all ignored
            "tau_arms": {"adaptive": {"examples_per_s_median": 0.01}},
            "frontier": {"adaptive_beats_tau0_throughput": False},
            "significance_filter": {"on": {"final_loss": 1e9}},
            "divergence_drill": {"reconverged": False},
        }
        rows, regressed = bench_diff.diff(new, self._priors())
        assert not regressed
        reported = {r["metric"] for r in rows}
        assert reported
        assert not reported & bench_diff.METADATA_SECTIONS

    def test_device_section_is_metadata_never_banded(self):
        """The device truth plane's `device` section carries roofline
        fracs and HBM high-water — capture-HARDWARE facts (they move
        with the chip, not the code) plus per-jit cost analyses. A
        catastrophic-looking device section must not flag; the
        import-time assert bars WATCHED from ever pointing into it
        (the PR 9 metadata-gate pattern)."""
        assert "device" in bench_diff.METADATA_SECTIONS
        assert not (
            {k for k, _ in bench_diff.WATCHED} & bench_diff.METADATA_SECTIONS
        )
        new = dict(bench_diff.load_record(fx("new_ok.json")))
        new["device"] = {  # chip-truth horrors, all ignored
            "recompiles_post_warmup": 1e9,
            "donation_fallbacks_total": 1e9,
            "functions": {"kv_push": {"compiles": 1e9}},
            "hbm": {"live_buffer_high_water_bytes": 1e18},
        }
        rows, regressed = bench_diff.diff(new, self._priors())
        assert not regressed
        reported = {r["metric"] for r in rows}
        assert reported
        assert not reported & bench_diff.METADATA_SECTIONS

    def test_blackbox_section_is_metadata_never_banded(self):
        """The flight-recorder `blackbox` section carries the overhead
        A/B's own paired medians and the drill bundle's host-dependent
        counts — run metadata, not a throughput the sentinel may band.
        A catastrophic-looking blackbox section must not flag; the
        import-time assert bars WATCHED from pointing into it."""
        assert "blackbox" in bench_diff.METADATA_SECTIONS
        assert not (
            {k for k, _ in bench_diff.WATCHED} & bench_diff.METADATA_SECTIONS
        )
        new = dict(bench_diff.load_record(fx("new_ok.json")))
        new["blackbox"] = {  # recorder horrors, all ignored
            "overhead": {"ratio_median": 1e9, "armed_ns_per_event": 1e12},
            "ring": {"dropped": 1e9},
            "bundles_captured": 1e9,
        }
        rows, regressed = bench_diff.diff(new, self._priors())
        assert not regressed
        reported = {r["metric"] for r in rows}
        assert reported
        assert not reported & bench_diff.METADATA_SECTIONS

    def test_learning_section_is_metadata_never_banded(self):
        """The learning truth plane's `learning` section carries loss /
        grad-norm trajectories, staleness histograms and heat shares —
        LEARNING evidence that moves with data and seeds, never a
        throughput the sentinel may band (a convergence trajectory
        banded as perf would flag every data change as a regression).
        The import-time assert bars WATCHED from pointing into it."""
        assert "learning" in bench_diff.METADATA_SECTIONS
        assert not (
            {k for k, _ in bench_diff.WATCHED} & bench_diff.METADATA_SECTIONS
        )
        new = dict(bench_diff.load_record(fx("new_ok.json")))
        new["learning"] = {  # divergence horrors, all ignored
            "probe": {
                "staleness": {"observed_max": 1e9, "within_bound": False},
                "shards": {"imbalance": 1e9},
                "trajectory_tail": [{"loss": 1e30, "grad_norm": 1e30}],
                "divergence_drill": {"fired": True},
            },
        }
        rows, regressed = bench_diff.diff(new, self._priors())
        assert not regressed
        reported = {r["metric"] for r in rows}
        assert reported
        assert not reported & bench_diff.METADATA_SECTIONS

    def test_history_section_is_metadata_never_banded(self):
        """The history plane's `history` section quotes the fold-hook
        A/B's own paired medians, the store's retention config, and
        live_drift — the run judging ITSELF against its own baseline.
        Banding any of it cross-run would double-count the e2e metric
        it rides on; a horror-valued section must not flag."""
        assert "history" in bench_diff.METADATA_SECTIONS
        assert not (
            {k for k, _ in bench_diff.WATCHED} & bench_diff.METADATA_SECTIONS
        )
        new = dict(bench_diff.load_record(fx("new_ok.json")))
        new["history"] = {  # drift/overhead horrors, all ignored
            "ab": {"ratio_median": 1e9, "fold_us_median": 1e12},
            "store": {"series": 1e9, "series_dropped": 1e9},
            "live_drift": {"drifting": True, "ratio": 0.01,
                           "verdict": "drift-down"},
        }
        rows, regressed = bench_diff.diff(new, self._priors())
        assert not regressed
        reported = {r["metric"] for r in rows}
        assert reported
        assert not reported & bench_diff.METADATA_SECTIONS

    def test_mesh_rebalance_sections_are_metadata_never_banded(self):
        """The `mesh` section is the auto-shaping disclosure (chosen
        factorization, 0-idle assertion) and `rebalance` is the
        live-repartitioning drill (imbalance before/after, rows moved,
        migration wall seconds, serve continuity, bit-parity verdict) —
        both host-dependent drill evidence, never throughput the
        sentinel may band."""
        assert "mesh" in bench_diff.METADATA_SECTIONS
        assert "rebalance" in bench_diff.METADATA_SECTIONS
        assert not (
            {k for k, _ in bench_diff.WATCHED} & bench_diff.METADATA_SECTIONS
        )
        new = dict(bench_diff.load_record(fx("new_ok.json")))
        new["mesh"] = {"devices_total": 8, "devices_used": 8, "idle": 0}
        new["rebalance"] = {  # drill horrors, all ignored
            "migration_seconds": 1e9,
            "rows_moved": 1e9,
            "imbalance_before": 1e9,
            "post_imbalance": 1e9,
            "serve": {"failed": 1e9},
        }
        rows, regressed = bench_diff.diff(new, self._priors())
        assert not regressed
        reported = {r["metric"] for r in rows}
        assert reported
        assert not reported & bench_diff.METADATA_SECTIONS


class TestCli:
    def test_flags_seeded_regression_exit_1(self):
        rc, lines = run_cli(
            "--new", fx("new_regressed.json"), "--records", *TRAJ
        )
        assert rc == 1
        assert lines[-1]["status"] == "REGRESSION"

    def test_passes_in_band_record_exit_0(self):
        rc, lines = run_cli("--new", fx("new_ok.json"), "--records", *TRAJ)
        assert rc == 0
        assert lines[-1]["status"] == "ok"
        assert lines[-1]["priors"] == 3  # outage + artifact skipped

    def test_default_mode_judges_newest_valid_against_earlier(self):
        rc, lines = run_cli("--records", *TRAJ, fx("new_regressed.json"))
        assert rc == 1  # newest valid record IS the regressed one

    def test_passes_the_real_committed_trajectory(self):
        """`make bench-diff` on this repo's BENCH_r*.json must be green
        — the sentinel guards the trajectory without inventing a
        regression out of the recorded history."""
        rc, lines = run_cli()
        assert rc == 0, lines
        assert lines[-1]["status"] in ("ok", "no-valid-records")

    def test_new_record_never_seeds_its_own_baseline(self):
        """A committed-but-regressed record judged via --new must not
        enter the priors it is compared against (it would pull the
        median toward itself and widen the spread-derived band)."""
        rc, lines = run_cli(
            "--new", fx("new_regressed.json"),
            "--records", *TRAJ, fx("new_regressed.json"),
        )
        assert rc == 1
        assert lines[-1]["priors"] == 3  # itself excluded, outage+artifact skipped

    def test_invalid_new_record_is_usage_error(self):
        rc, _ = run_cli(
            "--new", fx("traj_r2_outage.json"), "--records", *TRAJ
        )
        assert rc == 2
