"""Live repartitioning: KVVector.migrate + RebalanceController.

The contracts under test (ISSUE/PERFORMANCE.md "Declarative
partitioning", ROBUSTNESS.md "The backup barrier"):

- a migration moves rows online through the consistent-snapshot
  machinery — per-channel barrier timestamps bound which pushes are in
  the snapshot, journaled pushes past the barrier replay in order with
  translated slots;
- post-migration state is BIT-IDENTICAL to an undisturbed run (all
  parity checks here compare run-vs-run in base layout — never against
  arithmetic identities, which float accumulation order breaks);
- serving degrades (lock/queue latency) during the move, it never
  errors — a pull stream across the migration completes every request;
- recovery COMPOSES with migration: a restore landing mid-flight bumps
  the generation, the migration discards its stale image and
  re-snapshots, and no acked post-restore push is lost.

Every test runs on the conftest-forced 8-device CPU platform (`make
mesh-test` re-runs this file standalone under the same XLA_FLAGS).
"""

import threading
import time

import jax
import numpy as np
import pytest

from parameter_server_tpu.parallel import mesh as meshlib
from parameter_server_tpu.parallel import partition as partlib
from parameter_server_tpu.system import faults


@pytest.fixture(autouse=True)
def hermetic():
    from parameter_server_tpu.system.postoffice import Postoffice

    Postoffice.reset()
    faults.reset()
    yield
    faults.reset()
    Postoffice.reset()


def _store(num_data=4, num_server=2, num_slots=64, k=2, hashed=True,
           name="reb", keys=None):
    """A fresh KVVector on its own mesh (Postoffice untouched)."""
    from parameter_server_tpu.parameter.kv_vector import KVVector

    mesh = meshlib.make_mesh(num_data=num_data, num_server=num_server)
    kv = KVVector(mesh=mesh, k=k, num_slots=num_slots, hashed=hashed,
                  name=name)
    if keys is not None:
        kv.set_keys(0, keys)
    return kv


def _batches(n, k=2, seed=3, n_keys=40, key_space=997):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        keys = np.sort(
            rng.choice(key_space, size=n_keys, replace=False)
        ).astype(np.int64)
        vals = rng.normal(size=(n_keys, k)).astype(np.float32)
        out.append((keys, vals))
    return out


def _push_all(kv, batches):
    for keys, vals in batches:
        kv.push(kv.request(channel=0), keys=keys, values=vals)
    kv.executor.wait_all(pop=False)


def _perm(num_slots, seed=11):
    rng = np.random.default_rng(seed)
    return rng.permutation(num_slots).astype(np.int64)


class TestMigrate:
    def test_rejects_non_bijection(self):
        kv = _store(name="rej")
        with pytest.raises(ValueError, match="bijection"):
            kv.migrate(np.zeros(kv.num_slots, dtype=np.int64))
        with pytest.raises(ValueError, match="bijection"):
            kv.migrate(np.arange(kv.num_slots - 1))

    def test_bit_parity_vs_undisturbed_hashed(self):
        """Migrating mid-stream leaves the (base-layout) table
        bit-identical to a run that never migrated."""
        batches = _batches(6)
        perm = _perm(64)

        def run(migrate_at):
            kv = _store(name=f"mig{migrate_at}")
            for i, (keys, vals) in enumerate(batches):
                if i == migrate_at:
                    mig = kv.migrate(perm)
                    assert mig["rows_moved"] > 0
                kv.push(kv.request(channel=0), keys=keys, values=vals)
            kv.executor.wait_all(pop=False)
            return kv.get_replica()[0]

        undisturbed = run(migrate_at=None)
        migrated = run(migrate_at=3)
        assert undisturbed.tobytes() == migrated.tobytes()

    def test_pull_routing_and_values_survive_migration_exact_dir(self):
        """Exact directory: after the move, pulls by key return the
        same bytes as before — the remap routes lookups to the
        relocated rows."""
        keys = np.arange(40, dtype=np.int64)
        kv = _store(hashed=False, name="exact", keys=keys)
        _push_all(kv, [(keys, b) for _, b in _batches(3, n_keys=40)])
        before = kv.wait_pull(kv.pull(kv.request(channel=0), keys=keys))
        mig = kv.migrate(_perm(kv.num_slots, seed=5))
        assert mig["attempts"] == 1
        assert kv.layout(0) is not None
        after = kv.wait_pull(kv.pull(kv.request(channel=0), keys=keys))
        assert np.asarray(before).tobytes() == np.asarray(after).tobytes()
        # and the physical table really is permuted: channel table in
        # current layout != base-layout replica ordering
        base = kv.get_replica()[0]
        cur = np.asarray(kv.table(0, copy=True))
        assert base.tobytes() != cur.tobytes()
        np.testing.assert_array_equal(cur[kv.layout(0)], base)

    def test_composed_migrations_stack(self):
        """Two migrations compose (perm2[perm1]); pulls and the
        base-layout replica stay correct through both."""
        keys = np.arange(40, dtype=np.int64)
        batches = _batches(4, n_keys=40)
        kv = _store(hashed=False, name="twice", keys=keys)
        _push_all(kv, [(keys, b) for _, b in batches[:2]])
        kv.migrate(_perm(kv.num_slots, seed=1))
        _push_all(kv, [(keys, b) for _, b in batches[2:]])
        kv.migrate(_perm(kv.num_slots, seed=2))

        ref = _store(hashed=False, name="twice_ref", keys=keys)
        _push_all(ref, [(keys, b) for _, b in batches])
        assert kv.get_replica()[0].tobytes() == ref.get_replica()[0].tobytes()
        got = kv.wait_pull(kv.pull(kv.request(channel=0), keys=keys))
        want = ref.wait_pull(ref.pull(ref.request(channel=0), keys=keys))
        assert np.asarray(got).tobytes() == np.asarray(want).tobytes()

    def test_snapshot_roundtrip_across_migration(self):
        """Backups are layout-independent: a replica taken pre-move
        restores correctly post-move (set_replica re-applies the
        current perm)."""
        keys = np.arange(40, dtype=np.int64)
        batches = _batches(3, n_keys=40)
        kv = _store(hashed=False, name="roundtrip", keys=keys)
        _push_all(kv, [(keys, b) for _, b in batches])
        snap = kv.get_replica()
        kv.migrate(_perm(kv.num_slots, seed=9))
        kv.set_replica(snap)
        kv.executor.wait_all(pop=False)
        assert kv.get_replica()[0].tobytes() == snap[0].tobytes()
        got = kv.wait_pull(kv.pull(kv.request(channel=0), keys=keys))
        ref = _store(hashed=False, name="roundtrip_ref", keys=keys)
        _push_all(ref, [(keys, b) for _, b in batches])
        want = ref.wait_pull(ref.pull(ref.request(channel=0), keys=keys))
        assert np.asarray(got).tobytes() == np.asarray(want).tobytes()


class TestJournalReplay:
    def test_pushes_landing_mid_migration_replay_bit_identically(self):
        """Stall the migration between its snapshot and install
        (rebalance.migrate fault) while pushes keep landing: they are
        journaled, replayed past the barrier with translated slots, and
        the result is bit-identical to an undisturbed run."""
        keys = np.arange(40, dtype=np.int64)
        batches = _batches(4, n_keys=40)
        kv = _store(hashed=False, name="journal", keys=keys)
        _push_all(kv, [(keys, batches[0][1])])

        faults.arm("rebalance.migrate", kind="delay", delay_s=0.5,
                   once=True)
        result = {}
        t = threading.Thread(
            target=lambda: result.update(
                kv.migrate(_perm(kv.num_slots, seed=4))
            )
        )
        t.start()
        time.sleep(0.1)  # let the migration reach its stalled window
        for _, vals in batches[1:]:
            kv.push(kv.request(channel=0), keys=keys, values=vals)
        t.join(timeout=30)
        assert not t.is_alive()
        kv.executor.wait_all(pop=False)
        assert result["journaled"] >= 1
        assert result["replayed"] == result["journaled"]

        ref = _store(hashed=False, name="journal_ref", keys=keys)
        _push_all(ref, [(keys, b) for _, b in batches])
        assert kv.get_replica()[0].tobytes() == ref.get_replica()[0].tobytes()


class TestServeContinuity:
    def test_pull_stream_across_migration_completes_every_request(self):
        """Serving degrades (lock/queue latency) during the move — it
        NEVER errors: every pull issued while the migration stalls and
        flips returns the exact pre-migration bytes (no concurrent
        pushes, so any deviation is a routing bug)."""
        keys = np.arange(40, dtype=np.int64)
        kv = _store(hashed=False, name="serve", keys=keys)
        _push_all(kv, [(keys, b) for _, b in _batches(2, n_keys=40)])
        expect = np.asarray(
            kv.wait_pull(kv.pull(kv.request(channel=0), keys=keys))
        ).tobytes()

        faults.arm("rebalance.migrate", kind="delay", delay_s=0.4,
                   once=True)
        done = threading.Event()
        stats = {"ok": 0, "failed": 0}

        def serve():
            while not done.is_set():
                try:
                    got = kv.wait_pull(
                        kv.pull(kv.request(channel=0), keys=keys)
                    )
                    assert np.asarray(got).tobytes() == expect
                    stats["ok"] += 1
                except Exception:
                    stats["failed"] += 1

        server = threading.Thread(target=serve)
        server.start()
        try:
            mig = kv.migrate(_perm(kv.num_slots, seed=6))
        finally:
            done.set()
            server.join(timeout=30)
        assert mig["attempts"] == 1
        assert stats["failed"] == 0
        assert stats["ok"] > 0  # requests really flowed across the move


class TestRecoveryComposition:
    def test_restore_landing_mid_migration_forces_resnapshot(self):
        """Kill-one-shard recovery DURING a live migration: the restore
        bumps the generation, the stalled migration discards its stale
        image and retries, and the final table is bit-identical to the
        same recovery timeline without any migration — no acked
        post-restore push is lost, no pre-restore bytes resurrect."""
        from parameter_server_tpu.parameter.replica import ReplicaManager

        keys = np.arange(40, dtype=np.int64)
        batches = _batches(6, n_keys=40)

        def timeline(kv, rm, migrate):
            # pre-crash training, then the consistent backup
            _push_all(kv, [(keys, b) for _, b in batches[:2]])
            rm.backup_consistent(kv)
            result = {}
            t = None
            if migrate:
                faults.arm("rebalance.migrate", kind="delay",
                           delay_s=0.6, once=True)
                t = threading.Thread(
                    target=lambda: result.update(
                        kv.migrate(_perm(kv.num_slots, seed=8))
                    )
                )
                t.start()
                time.sleep(0.1)  # migration now stalled post-snapshot
            # updates that the recovery will wipe (post-backup, pre-
            # restore — the recovery drill's semantics)...
            _push_all(kv, [(keys, batches[2][1])])
            # ...the shard dies and the snapshot is restored THROUGH
            # the executor (live path: note_external_restore fires)
            assert rm.recover(kv, through_executor=True)
            # acked post-restore updates — these must survive
            for _, vals in (b for b in batches[3:]):
                kv.push(kv.request(channel=0), keys=keys, values=vals)
            if t is not None:
                t.join(timeout=30)
                assert not t.is_alive()
            kv.executor.wait_all(pop=False)
            return result

        kv_ref = _store(hashed=False, name="rec_ref", keys=keys)
        timeline(kv_ref, ReplicaManager(), migrate=False)
        ref = kv_ref.get_replica()[0]

        kv = _store(hashed=False, name="rec_mig", keys=keys)
        result = timeline(kv, ReplicaManager(), migrate=True)
        assert result["attempts"] >= 2  # the stale image was discarded
        assert kv.layout(0) is not None  # ...and the move still landed
        assert kv.get_replica()[0].tobytes() == ref.tobytes()

    def test_migrate_gives_up_after_max_attempts(self):
        kv = _store(name="giveup")
        _push_all(kv, _batches(1))
        orig = kv.snapshot

        def poisoned(ch=0, callback=None):
            kv.note_external_restore()  # every snapshot is born stale
            return orig(ch, callback)

        kv.snapshot = poisoned
        with pytest.raises(RuntimeError, match="could not complete"):
            kv.migrate(_perm(kv.num_slots), max_attempts=2)
        kv.snapshot = orig
        # the store still serves after the failed migration
        kv.executor.wait_all(pop=False)
        assert kv.layout(0) is None


class TestKeyHeatRebase:
    def test_rebase_translates_candidates_and_resets_window(self):
        from parameter_server_tpu.telemetry.learning import KeyHeat

        heat = KeyHeat(num_slots=64, num_shards=8, top_k=16,
                       decay_every=1 << 30)
        hot = np.arange(8)  # all of shard 0
        heat.note(np.repeat(hot, 40))
        assert heat.shares()["imbalance"] == pytest.approx(8.0)
        assert {h["slot"] for h in heat.top_slots()} == set(hot.tolist())

        perm = np.arange(64)
        perm[0], perm[63] = 63, 0  # slot 0 relocated to shard 7
        heat.rebase(perm)
        # the window reset: no weight, no imbalance reading
        s = heat.shares()
        assert s["total_weight"] == 0.0 and s["imbalance"] is None
        # candidates translated across the layout change
        assert 63 in {h["slot"] for h in heat.top_slots()} or not heat.top_slots()
        # post-rebalance traffic for the SAME keys lands spread out
        heat.note(np.repeat(perm[hot], 40))
        counts_max_over_mean = heat.shares()["imbalance"]
        assert counts_max_over_mean < 8.0


class TestRebalanceController:
    def test_alert_fires_controller_rebalances_and_imbalance_recovers(self):
        """End-to-end on 8 server shards: heat-skewed traffic → the
        shipped shard_imbalance rule (threshold 4.0, for 5 s) reaches
        firing → the attached controller plans from the measured
        hot-slot/load-share tables and migrates online → post-rebalance
        traffic re-measures below threshold → table bit-identical to an
        undisturbed run."""
        from parameter_server_tpu.telemetry import alerts as alerts_mod
        from parameter_server_tpu.telemetry import (
            registry as telemetry_registry,
        )
        from parameter_server_tpu.telemetry.instruments import (
            learning_instruments,
        )
        from parameter_server_tpu.telemetry.learning import KeyHeat

        keys = np.arange(48, dtype=np.int64)
        batches = _batches(3, n_keys=48)
        # 1x8 mesh: 8 server shards (max/mean tops out at num_shards,
        # so the shipped threshold 4.0 NEEDS > 4 shards to be exceeded)
        kv = _store(num_data=1, num_server=8, hashed=False, name="ctl",
                    keys=keys)
        assert kv.num_slots == 64
        _push_all(kv, [(keys, b) for _, b in batches])

        heat = KeyHeat(num_slots=64, num_shards=8, top_k=16,
                       decay_every=1 << 30)
        hot = np.arange(8)  # keys 0..7 → slots 0..7: all of shard 0
        for _ in range(4):
            heat.note(np.repeat(hot, 25))
        imb0 = heat.shares()["imbalance"]
        assert imb0 > 4.0

        ctl = partlib.RebalanceController(kv, heat)
        assert ctl.threshold == 4.0  # read from the shipped rule
        assert ctl.should_rebalance()

        reg = telemetry_registry.default_registry()
        gauge = learning_instruments(reg)["shard_imbalance"]
        gauge.set(imb0)
        mgr = alerts_mod.AlertManager(alerts_mod.default_rules(),
                                      registry=reg)
        ctl.attach(mgr)
        assert ctl.history() == []
        mgr.evaluate(now=0.0)  # breach observed → pending
        assert ctl.history() == []  # for_s dwell: not yet
        mgr.evaluate(now=6.0)  # past for_s=5 → firing → rebalance
        hist = ctl.history()
        assert len(hist) == 1
        rec = hist[0]
        assert rec["rows_moved"] > 0
        assert rec["imbalance_before"] == pytest.approx(imb0)
        assert rec["predicted_imbalance"] < 4.0
        assert kv.layout(0) is not None

        # post-rebalance traffic (same hot keys, new layout) stays
        # below the alert threshold
        perm = kv.layout(0)
        for _ in range(4):
            heat.note(np.repeat(perm[hot], 25))
        post = ctl.refresh_post_imbalance()
        assert post is not None and post < 4.0

        # the moved table still matches an undisturbed run bit-for-bit
        ref = _store(num_data=1, num_server=8, hashed=False,
                     name="ctl_ref", keys=keys)
        _push_all(ref, [(keys, b) for _, b in batches])
        assert kv.get_replica()[0].tobytes() == ref.get_replica()[0].tobytes()

        # firing → firing does not re-trigger; a second firing edge
        # after the heat window rebased (imbalance gone) is a no-op
        mgr.evaluate(now=12.0)
        assert len(ctl.history()) == 1

    def test_execute_is_noop_below_threshold(self):
        from parameter_server_tpu.telemetry.learning import KeyHeat

        kv = _store(num_data=1, num_server=8, name="noop")
        heat = KeyHeat(num_slots=kv.num_slots, num_shards=8,
                       decay_every=1 << 30)
        heat.note(np.arange(64))  # perfectly uniform
        ctl = partlib.RebalanceController(kv, heat)
        assert not ctl.should_rebalance()
        assert ctl.execute() is None
        assert kv.layout(0) is None

    def test_plan_rebalance_is_deterministic_and_bijective(self):
        from parameter_server_tpu.telemetry.learning import KeyHeat

        def mk():
            heat = KeyHeat(num_slots=64, num_shards=8, top_k=16,
                           decay_every=1 << 30)
            heat.note(np.repeat(np.arange(8), 30))
            return heat

        p1 = partlib.plan_rebalance(mk(), 64, 8)
        p2 = partlib.plan_rebalance(mk(), 64, 8)
        assert p1 is not None
        np.testing.assert_array_equal(p1.perm, p2.perm)
        np.testing.assert_array_equal(np.sort(p1.perm), np.arange(64))
        assert p1.rows_moved == 2 * len(p1.moves)  # swaps, not drops
        assert p1.predicted_imbalance < p1.imbalance_before

    def test_plan_rebalance_declines_single_shard_and_balance(self):
        from parameter_server_tpu.telemetry.learning import KeyHeat

        heat = KeyHeat(num_slots=64, num_shards=1, decay_every=1 << 30)
        heat.note(np.repeat(np.arange(8), 30))
        assert partlib.plan_rebalance(heat, 64, 1) is None
        cold = KeyHeat(num_slots=64, num_shards=8, decay_every=1 << 30)
        assert partlib.plan_rebalance(cold, 64, 8) is None
