#!/usr/bin/env bash
# Thin app launcher (ref script/ps.sh): run a linear-method config.
#   script/ps.sh <config.conf> [main.py args...]
set -euo pipefail
conf=${1:?usage: ps.sh <config.conf> [args...]}; shift
exec python -m parameter_server_tpu.apps.linear.main "$conf" "$@"
