#!/usr/bin/env python
"""donation-lint: keep the zero-copy data plane zero-copy (fast, static).

Every ``jax.jit`` site in the data-plane modules below must either
declare ``donate_argnums`` (any value — an explicit empty tuple is a
recorded decision) or carry a ``# no-donate: <reason>`` comment within
three lines of the call. The rule exists because the defensive-copy
trap is silent: a jitted table update WITHOUT donation compiles, runs,
and quietly materializes a full ``[P, k]`` copy in HBM per call — the
exact regression PR 2 removed (doc/PERFORMANCE.md "Donation rules").
The lint makes the choice explicit at every site instead of trusting
review to notice a missing kwarg.

Purely syntactic (ast + source lines): no jax import, no tracing.
Runs as the ``donation`` pass of the pslint static-analysis suite
(``make pslint``, doc/STATIC_ANALYSIS.md) — the logic lives here as
the single source of truth and pslint wraps it. ``make donation-lint``
aliases the single-pass pslint run; this file also stays directly
runnable and is exercised as a tier-1 test in tests/test_donation.py
so drift fails CI before it ships.
"""

from __future__ import annotations

import ast
import os
import sys

# the data-plane surface: modules whose jits touch parameter tables /
# optimizer state on the hot path
SCOPE = (
    "parameter_server_tpu/ops/kv_ops.py",
    "parameter_server_tpu/ops/ftrl.py",
    "parameter_server_tpu/ops/ftrl_sparse.py",
    "parameter_server_tpu/parameter/parameter.py",
    "parameter_server_tpu/parameter/kv_vector.py",
    "parameter_server_tpu/parameter/kv_map.py",
    "parameter_server_tpu/parameter/kv_layer.py",
    "parameter_server_tpu/apps/linear/async_sgd.py",
    "parameter_server_tpu/apps/linear/updaters.py",
    "parameter_server_tpu/apps/nn/trainer.py",
)

MARKER = "no-donate:"
COMMENT_REACH = 3  # lines above the statement the justification may sit


def _is_jit_ref(node: ast.AST) -> bool:
    """``jax.jit`` / bare ``jit`` as a reference (not a call)."""
    if isinstance(node, ast.Attribute) and node.attr == "jit":
        return True
    return isinstance(node, ast.Name) and node.id == "jit"


def _jit_call_keywords(node: ast.Call):
    """If ``node`` is a jit(...) or partial(jax.jit, ...) call, return
    its keyword list; else None."""
    if _is_jit_ref(node.func):
        return node.keywords
    # functools.partial(jax.jit, ...) — keywords live on the partial
    if (
        isinstance(node.func, ast.Attribute) and node.func.attr == "partial"
        or isinstance(node.func, ast.Name) and node.func.id == "partial"
    ):
        if node.args and _is_jit_ref(node.args[0]):
            return node.keywords
    return None


def _declares_donation(keywords) -> bool:
    return any(kw.arg == "donate_argnums" for kw in keywords)


def _has_marker(lines, lineno: int, end_lineno: int) -> bool:
    lo = max(1, lineno - COMMENT_REACH)
    hi = min(len(lines), end_lineno)
    return any(MARKER in lines[i - 1] for i in range(lo, hi + 1))


def _lint_file(path: str, rel: str) -> list:
    with open(path, "r", encoding="utf-8") as f:
        src = f.read()
    lines = src.splitlines()
    problems = []
    tree = ast.parse(src, filename=rel)
    for node in ast.walk(tree):
        sites = []
        if isinstance(node, ast.Call):
            kws = _jit_call_keywords(node)
            if kws is not None and not _declares_donation(kws):
                sites.append(node)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # bare @jax.jit decorator (no call — can't carry kwargs)
            for dec in node.decorator_list:
                if _is_jit_ref(dec):
                    sites.append(dec)
        for site in sites:
            end = getattr(site, "end_lineno", site.lineno) or site.lineno
            if not _has_marker(lines, site.lineno, end):
                problems.append(
                    f"{rel}:{site.lineno}: jit site neither declares "
                    f"donate_argnums nor carries a '# {MARKER} <reason>' "
                    "justification"
                )
    return problems


def lint(root: str | None = None) -> list:
    """Returns a list of problem strings (empty = clean)."""
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    problems = []
    for rel in SCOPE:
        path = os.path.join(root, rel)
        if not os.path.exists(path):
            problems.append(f"{rel}: scoped data-plane module is missing")
            continue
        try:
            problems.extend(_lint_file(path, rel))
        except SyntaxError as e:
            problems.append(f"{rel}: failed to parse: {e}")
    return problems


def main() -> int:
    problems = lint()
    if problems:
        for p in problems:
            print(f"donation-lint: {p}", file=sys.stderr)
        print(
            f"donation-lint: FAILED ({len(problems)} problems)",
            file=sys.stderr,
        )
        return 1
    print("donation-lint: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
