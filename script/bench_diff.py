#!/usr/bin/env python
"""bench_diff: regression sentinel over the BENCH_r*.json trajectory.

Compares a new bench record against the prior records' trajectory and
exits nonzero when a watched throughput metric lands out of band — the
automated version of the per-round VERDICT eyeball, so a perf PR that
silently costs 30% of e2e throughput fails `make bench-diff` instead of
shipping.

Noise discipline (the ROADMAP bench invariant): every quoted number in
a record is already the MEDIAN of back-to-back paired reps/windows, and
this tool compares the new value against the MEDIAN of the prior valid
records — never best-of, never a single A/B. The tolerance band is
derived from the trajectory's own observed spread (how far priors sit
from their median), floored at ``--band-floor`` (default 20%: this
host's CPU capacity flaps seconds-scale) and capped at ``--band-cap``
(a trajectory that noisy cannot alibi arbitrary regressions).

Record handling: accepts both raw bench records and the round driver's
wrapper shape (``{"n", "cmd", "rc", "tail", "parsed"}`` — the committed
BENCH_r*.json files). Failure records (``error`` set, or no watched
metric > 0) are skipped: an unreachable-accelerator round is an outage,
not a baseline.

    python script/bench_diff.py                 # repo BENCH_r*.json:
                                                # newest valid vs priors
    python script/bench_diff.py --new NEW.json --records A.json B.json
    make bench-diff

Exit codes: 0 in band (or no baseline yet) / 1 regression / 2 usage.
One JSON report line per watched metric plus a summary line, so CI logs
stay machine-parseable.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import statistics
import sys
from typing import Dict, List, Optional, Tuple

#: watched throughput metrics, in report order (only those present in
#: both the new record and >=1 prior are compared)
WATCHED = (
    ("value", "device-only examples/sec (headline)"),
    ("e2e_median_window", "e2e examples/sec, median window (synthetic)"),
    ("e2e_stream", "e2e examples/sec (--real stream)"),
)

#: record sections that are drill/A-B METADATA, not throughput metrics
#: the sentinel may band: ``recovery`` carries MTTR/degraded counts
#: whose host-dependent wall times would false-flag every round, and
#: the embedded A/B sections quote their own paired medians with their
#: own disclosure. A WATCHED key must never point into one of these —
#: enforced at import so a future metric addition cannot silently band
#: drill metadata.
METADATA_SECTIONS = frozenset(
    {
        "recovery",
        "serve",
        "wire",
        "host_ingest",
        "kv_dataplane",
        "ftrl_sparse",
        # continuous-batching decode A/B: quotes its own paired-rep
        # medians (batched vs sequential tokens/s under churn) with the
        # on-chip target stated in-record — self-disclosing A/B, not a
        # series the sentinel may band
        "decode_batching",
        "attribution",
        "telemetry",
        # the --expose-port self-scrape summary (node list, series-line
        # count, alerts firing at teardown) — run metadata, not a
        # throughput the sentinel may band
        "expose",
        # the device truth plane (per-jit cost analysis, recompile /
        # donation-fallback counts, HBM high-water, roofline
        # cross-checks) — capture-HARDWARE facts: fracs of peak move
        # with the chip the record was taken on, not with the code,
        # so banding them would false-flag every capture-host change
        "device",
        # which wire the e2e stream rode (config + per-encoding
        # bytes/example + pinned lane statics + fallback counts — both
        # the synthetic and the --real records carry it since the
        # stream-once wire flip): disclosure metadata, not a
        # throughput the sentinel may band
        "e2e_wire",
        "e2e_upload_cache",
        # flight-recorder evidence (telemetry/blackbox.py): the
        # steady-state overhead A/B quotes its own paired medians with
        # its own disclosure, the drill's auto-captured bundle summary
        # carries host-dependent counts — banding either would
        # false-flag every round
        "blackbox",
        # the learning truth plane (telemetry/learning.py): realized
        # staleness, key-heat shard shares, loss/grad-norm convergence
        # trajectories, the divergence drill — LEARNING evidence, not
        # throughput; banding a loss trajectory as perf would flag
        # every data/seed change as a regression
        "learning",
        # the history plane (telemetry/history.py): the fold-hook
        # overhead A/B quotes its own paired medians, the store
        # snapshot is retention config, and live_drift is the run
        # judging ITSELF (tail vs its own baseline) — banding any of
        # it cross-run would double-count the e2e metric it rides on
        "history",
        # mesh shape disclosure (parallel/mesh.py auto-shaping): which
        # (data, server) factorization was chosen and that 0 devices
        # idle — capture-host facts, asserted in the record itself,
        # not a throughput the sentinel may band
        "mesh",
        # the live-rebalance drill (parallel/partition.py
        # RebalanceController + KVVector.migrate): imbalance
        # before/after, rows moved, migration wall seconds, serve
        # continuity counts, the bit-parity verdict — drill evidence
        # with host-dependent wall times; banding it would false-flag
        # every round
        "rebalance",
        # self-driving consistency (adaptive τ + KKT filter): quotes
        # its own paired-rep A/B medians (τ arms, filter off/on key
        # and byte reductions) plus the divergence-drill episode —
        # self-disclosing, never banded by the sentinel
        "consistency",
    }
)
assert not ({k for k, _ in WATCHED} & METADATA_SECTIONS), (
    "WATCHED must not band metadata sections"
)


def load_record(path: str) -> Optional[dict]:
    """The bench record inside ``path`` (unwrapping the round driver's
    {parsed: ...} shape), or None if unreadable."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(data, dict):
        return None
    if "parsed" in data and isinstance(data.get("parsed"), (dict, type(None))):
        data = data["parsed"]
    return data if isinstance(data, dict) else None


#: fields every record measured under the flushed-window protocol
#: carries (round 2's MEASUREMENT NOTE in bench.py: round 1's 5.25M was
#: a dispatch-rate artifact — ``block_until_ready`` under-waits on the
#: tunneled backend, so pre-protocol numbers are not comparable and
#: must not seed the baseline)
PROTOCOL_FIELDS = (
    "steps_per_launch_best",
    "e2e_median_window",
    "e2e_stream",
    "breakdown_bound",
    "attribution",
)


def is_valid(rec: Optional[dict]) -> bool:
    """A usable, protocol-comparable measurement: no failure marker,
    >=1 watched metric > 0, and measured under the flushed-window
    protocol (schema gate: any PROTOCOL_FIELDS present)."""
    if not rec or rec.get("error"):
        return False
    if not any(k in rec for k in PROTOCOL_FIELDS):
        return False
    return any(
        isinstance(rec.get(k), (int, float)) and rec.get(k) > 0
        for k, _ in WATCHED
    )


def _round_key(path: str) -> Tuple[int, str]:
    m = re.search(r"BENCH_r(\d+)", os.path.basename(path))
    return (int(m.group(1)) if m else 1 << 30, path)


def discover_trajectory(root: str) -> List[str]:
    return sorted(glob.glob(os.path.join(root, "BENCH_r*.json")), key=_round_key)


def band_for(priors: List[float], floor: float, cap: float) -> float:
    """Tolerance band from the trajectory's own spread: the maximum
    relative deviation of any prior from the prior median, widened 1.5x
    (one-sided safety), floored and capped."""
    med = statistics.median(priors)
    if med <= 0:
        return cap
    max_dev = max(abs(v - med) / med for v in priors)
    return max(floor, min(cap, 1.5 * max_dev))


def diff(
    new: dict,
    priors: List[dict],
    band_floor: float = 0.20,
    band_cap: float = 0.45,
) -> Tuple[List[dict], bool]:
    """Per-metric comparison rows + overall regression flag."""
    rows: List[dict] = []
    regressed = False
    for key, desc in WATCHED:
        if key in METADATA_SECTIONS:  # second line of defense behind
            continue  # the import-time assert: never band drill metadata
        new_v = new.get(key)
        if not isinstance(new_v, (int, float)) or new_v <= 0:
            continue
        prior_vs = [
            r[key]
            for r in priors
            if isinstance(r.get(key), (int, float)) and r[key] > 0
        ]
        row: Dict = {"metric": key, "description": desc, "new": new_v}
        if not prior_vs:
            row["status"] = "no-baseline"
            rows.append(row)
            continue
        baseline = statistics.median(prior_vs)
        band = band_for(prior_vs, band_floor, band_cap)
        ratio = new_v / baseline
        row.update(
            {
                "baseline_median": round(baseline, 1),
                "priors": len(prior_vs),
                "ratio": round(ratio, 3),
                "band": round(band, 3),
            }
        )
        if ratio < 1.0 - band:
            row["status"] = "REGRESSION"
            regressed = True
        else:
            row["status"] = "ok" if ratio <= 1.0 + band else "improved"
        rows.append(row)
    return rows, regressed


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="bench_diff", description=__doc__)
    ap.add_argument(
        "--new",
        help="record to judge (default: newest VALID record of --records)",
    )
    ap.add_argument(
        "--records",
        nargs="*",
        help="trajectory record files, oldest first (default: the repo's "
        "BENCH_r*.json sorted by round)",
    )
    ap.add_argument("--band-floor", type=float, default=0.20)
    ap.add_argument("--band-cap", type=float, default=0.45)
    args = ap.parse_args(argv)

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    paths = (
        list(args.records)
        if args.records
        else discover_trajectory(root)
    )
    trajectory = [
        (p, load_record(p)) for p in paths
    ]
    valid = [(p, r) for p, r in trajectory if is_valid(r)]

    if args.new:
        new_rec = load_record(args.new)
        if not is_valid(new_rec):
            print(
                f"bench_diff: --new {args.new} is not a valid measurement "
                "record",
                file=sys.stderr,
            )
            return 2
        new_name = args.new
        # the record under judgment must not seed its own baseline: a
        # committed-but-regressed BENCH_r*.json judged via --new would
        # otherwise pull the median toward itself and widen the band
        new_real = os.path.realpath(args.new)
        priors = [r for p, r in valid if os.path.realpath(p) != new_real]
    else:
        if not valid:
            print(
                json.dumps(
                    {
                        "summary": "bench_diff",
                        "status": "no-valid-records",
                        "records_seen": len(trajectory),
                    }
                )
            )
            return 0
        new_name, new_rec = valid[-1]
        priors = [r for _, r in valid[:-1]]

    rows, regressed = diff(
        new_rec, priors, band_floor=args.band_floor, band_cap=args.band_cap
    )
    for row in rows:
        print(json.dumps(row))
    print(
        json.dumps(
            {
                "summary": "bench_diff",
                "new": os.path.basename(new_name),
                "priors": len(priors),
                "status": "REGRESSION" if regressed else "ok",
            }
        )
    )
    return 1 if regressed else 0


if __name__ == "__main__":
    sys.exit(main())
