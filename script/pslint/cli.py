#!/usr/bin/env python
"""pslint CLI: run the multi-pass static-analysis suite.

    python script/pslint/cli.py              # all passes, repo root
    python script/pslint/cli.py --rules locks,threads
    python script/pslint/cli.py --list       # show registered passes
    python script/pslint/cli.py --timings --budget 60   # CI shape

Findings print one per line as ``path:line rule message`` (clickable
in editors); exit 0 = clean, 1 = unsuppressed findings, 2 = usage or
internal error (or budget exceeded with --budget). Run via ``make
pslint`` (aggregate) — ``make metrics-lint`` / ``make donation-lint``
alias single passes.

Per-file passes cache their findings by content hash in
``.pslint-cache.json`` at the repo root (gitignored); ``--no-cache``
forces a cold run, which is also what ``--budget`` is calibrated
against.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pslint.engine import Engine, default_rules  # noqa: E402

CACHE_BASENAME = ".pslint-cache.json"


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="pslint", description=__doc__)
    parser.add_argument(
        "--rules",
        help="comma-separated pass names to run (default: all)",
    )
    parser.add_argument(
        "--root",
        default=os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        ),
        help="repository root (default: this checkout)",
    )
    parser.add_argument(
        "--list", action="store_true", help="list registered passes and exit"
    )
    parser.add_argument(
        "--timings",
        action="store_true",
        help="report per-pass wall-clock and cache hit counts",
    )
    parser.add_argument(
        "--budget",
        type=float,
        default=None,
        metavar="SECONDS",
        help="fail (exit 2) if total analysis wall-clock exceeds this "
        "(CI keeps the suite honest about staying fast)",
    )
    parser.add_argument(
        "--no-cache",
        action="store_true",
        help="ignore and do not write the incremental cache (cold run)",
    )
    parser.add_argument(
        "--cache-path",
        default=None,
        help=f"cache file location (default: <root>/{CACHE_BASENAME})",
    )
    args = parser.parse_args(argv)

    try:
        rules = default_rules(
            args.rules.split(",") if args.rules else None
        )
    except ValueError as e:
        print(f"pslint: {e}", file=sys.stderr)
        return 2
    if args.list:
        for r in rules:
            print(r.name)
        return 0

    cache_path = None
    if not args.no_cache:
        cache_path = args.cache_path or os.path.join(
            args.root, CACHE_BASENAME
        )

    t0 = time.perf_counter()
    engine = Engine(args.root, rules, cache_path=cache_path)
    try:
        findings, suppressed = engine.run()
    except Exception as e:  # engine bug, unreadable tree, ...
        print(f"pslint: internal error: {type(e).__name__}: {e}", file=sys.stderr)
        return 2
    elapsed = time.perf_counter() - t0

    for f in findings:
        print(f.format())
    if args.timings:
        for name in sorted(engine.timings, key=engine.timings.get, reverse=True):
            st = engine.stats.get(name, {})
            print(
                f"pslint: timing {name}: {engine.timings[name]:.3f}s "
                f"(analyzed {st.get('analyzed', 0)}, "
                f"cached {st.get('cached', 0)})",
                file=sys.stderr,
            )
        print(f"pslint: timing total: {elapsed:.3f}s", file=sys.stderr)
    names = ",".join(r.name for r in rules)
    if args.budget is not None and elapsed > args.budget:
        print(
            f"pslint: BUDGET EXCEEDED: {elapsed:.1f}s > {args.budget:.1f}s "
            f"[{names}] — profile with --timings; the suite must stay "
            "inside its stated wall-clock",
            file=sys.stderr,
        )
        return 2
    if findings:
        print(
            f"pslint: FAILED ({len(findings)} findings, "
            f"{suppressed} suppressed) [{names}]",
            file=sys.stderr,
        )
        return 1
    print(f"pslint: OK ({suppressed} suppressed) [{names}]")
    return 0


if __name__ == "__main__":
    sys.exit(main())
