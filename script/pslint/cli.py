#!/usr/bin/env python
"""pslint CLI: run the multi-pass static-analysis suite.

    python script/pslint/cli.py              # all passes, repo root
    python script/pslint/cli.py --rules locks,threads
    python script/pslint/cli.py --list       # show registered passes

Findings print one per line as ``path:line rule message`` (clickable
in editors); exit 0 = clean, 1 = unsuppressed findings, 2 = usage or
internal error. Run via ``make pslint`` (aggregate) — ``make
metrics-lint`` / ``make donation-lint`` alias single passes.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pslint.engine import Engine, default_rules  # noqa: E402


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="pslint", description=__doc__)
    parser.add_argument(
        "--rules",
        help="comma-separated pass names to run (default: all)",
    )
    parser.add_argument(
        "--root",
        default=os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        ),
        help="repository root (default: this checkout)",
    )
    parser.add_argument(
        "--list", action="store_true", help="list registered passes and exit"
    )
    args = parser.parse_args(argv)

    try:
        rules = default_rules(
            args.rules.split(",") if args.rules else None
        )
    except ValueError as e:
        print(f"pslint: {e}", file=sys.stderr)
        return 2
    if args.list:
        for r in rules:
            print(r.name)
        return 0

    try:
        findings, suppressed = Engine(args.root, rules).run()
    except Exception as e:  # engine bug, unreadable tree, ...
        print(f"pslint: internal error: {type(e).__name__}: {e}", file=sys.stderr)
        return 2

    for f in findings:
        print(f.format())
    names = ",".join(r.name for r in rules)
    if findings:
        print(
            f"pslint: FAILED ({len(findings)} findings, "
            f"{suppressed} suppressed) [{names}]",
            file=sys.stderr,
        )
        return 1
    print(f"pslint: OK ({suppressed} suppressed) [{names}]")
    return 0


if __name__ == "__main__":
    sys.exit(main())
