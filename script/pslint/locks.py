"""Lock-discipline race detector (pass ``locks``).

The concurrency surface declares which lock protects each shared
mutable attribute with a ``# guarded-by: <lockattr>`` comment on the
attribute's initialization (trailing, or on the line directly above):

    self._pending = {}  # guarded-by: _cv

The pass then flags every read or write of a guarded attribute that is
not lexically inside a ``with self.<lock>:`` block for the declared
lock. Escapes, in discipline order:

- ``__init__`` / ``__del__`` bodies are exempt (construction and
  teardown happen-before/after sharing);
- a method whose entire body runs with the lock already held declares
  it with ``# holds-lock: <lockattr>`` on (or directly above) its
  ``def`` line — the convention behind the repo's ``*_locked`` method
  names, made checkable;
- ``# pslint: disable=guarded-access — <reason>`` for the rare
  deliberate lock-free access (single-writer counters and the like).

Lock model (purely syntactic, per class):

- a *lock* is any attribute assigned ``threading.Lock()``, ``RLock()``
  or ``Condition()`` in the class (instance or class-level);
- ``threading.Condition(self._x)`` ALIASES ``_x``: acquiring the
  condition acquires the wrapped lock, so either satisfies a guard on
  the other;
- nested ``def``s drop the held-lock set (they may escape the block
  and run on another thread — a Thread target defined under a lock is
  NOT protected by it); ``lambda``s keep it (the ``Condition.wait_for``
  predicate idiom runs with the lock held).

**Lock-order graph.** Acquiring lock B while holding lock A adds the
edge A→B; edges are also derived one call level deep — a call made
while holding A, to a method of self or of a typed attribute
(``self.x = ClassName(...)`` in ``__init__`` types ``x``), contributes
A→{locks that method acquires directly}. A cycle in the resulting
directed graph is a potential deadlock (rule ``lock-order``); the
repo's invariant is that the graph stays acyclic.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Sequence, Set, Tuple

from .engine import (
    GUARDED_BY_RE,
    HOLDS_LOCK_RE,
    LOCK_FACTORIES,
    ClassModel,
    Finding,
    Rule,
    direct_acquires,
    self_attr,
)

# the concurrency surface: every module with threads or locks on the
# training/system path (doc/STATIC_ANALYSIS.md "Scope")
SCOPE = (
    "parameter_server_tpu/system/executor.py",
    "parameter_server_tpu/system/postoffice.py",
    "parameter_server_tpu/system/heartbeat.py",
    "parameter_server_tpu/system/aux_runtime.py",
    "parameter_server_tpu/system/dashboard.py",
    "parameter_server_tpu/system/recovery.py",
    "parameter_server_tpu/system/monitor.py",
    "parameter_server_tpu/system/faults.py",
    "parameter_server_tpu/telemetry/aggregate.py",
    "parameter_server_tpu/telemetry/alerts.py",
    "parameter_server_tpu/telemetry/blackbox.py",
    "parameter_server_tpu/telemetry/device.py",
    "parameter_server_tpu/telemetry/exposition.py",
    "parameter_server_tpu/telemetry/history.py",
    "parameter_server_tpu/telemetry/learning.py",
    "parameter_server_tpu/utils/concurrent.py",
    "parameter_server_tpu/parallel/partition.py",
    "parameter_server_tpu/parameter/parameter.py",
    "parameter_server_tpu/parameter/kv_vector.py",
    "parameter_server_tpu/parameter/replica.py",
    "parameter_server_tpu/serving/admission.py",
    "parameter_server_tpu/serving/batcher.py",
    "parameter_server_tpu/serving/coalescer.py",
    "parameter_server_tpu/serving/frontend.py",
    "parameter_server_tpu/serving/loadgen.py",
    "parameter_server_tpu/serving/replica.py",
    "parameter_server_tpu/system/autoscale.py",
    "parameter_server_tpu/learner/ingest.py",
    "parameter_server_tpu/learner/workload_pool.py",
    "parameter_server_tpu/learner/wire.py",
    "parameter_server_tpu/learner/consistency.py",
    "parameter_server_tpu/apps/linear/async_sgd.py",
)

# engine-hosted symbol-table pieces, re-exported for existing callers
_ClassModel = ClassModel
_self_attr = self_attr
_direct_acquires = direct_acquires


class LockDisciplineRule(Rule):
    name = "locks"

    def __init__(self, scope: Sequence[str] = SCOPE):
        self.scope = tuple(scope)

    def paths(self, root: str) -> Sequence[str]:
        return self.scope

    def check(self, files, root: str) -> List[Finding]:
        findings: List[Finding] = []
        # EVERY class is modeled and checked, even when two scope files
        # reuse a name — a name-keyed dict would silently drop one
        # class from all checking. Cross-class call resolution uses
        # the by-name index and simply skips ambiguous names
        # (conservative: no edges rather than wrong-class edges).
        project = self.get_project(files)
        all_models: List[_ClassModel] = []
        for rel in files:
            all_models.extend(project.classes(rel))
        models: Dict[str, _ClassModel] = {}
        ambiguous: set = set()
        for m in all_models:
            if m.name in ambiguous:
                continue
            if m.name in models:
                del models[m.name]
                ambiguous.add(m.name)
            else:
                models[m.name] = m

        # validate guard declarations before checking accesses
        for model in all_models:
            for attr, (lock, line) in model.guards.items():
                if model.canonical(lock) not in {
                    model.canonical(l) for l in model.locks
                }:
                    findings.append(
                        Finding(
                            model.sf.rel,
                            line,
                            "unknown-lock",
                            f"{model.name}.{attr} declares guarded-by: "
                            f"{lock}, but {lock} is not a threading.Lock/"
                            "RLock/Condition attribute of the class",
                        )
                    )

        # edge -> (path, line) of the acquisition that created it
        edges: Dict[Tuple[str, str], Tuple[str, int]] = {}

        for model in all_models:
            acquires = {
                name: _direct_acquires(fn, model)
                for name, fn in model.methods.items()
            }
            for mname, fn in model.methods.items():
                if mname in ("__init__", "__del__"):
                    continue
                held0: Set[str] = set()
                order0: List[str] = []
                m = HOLDS_LOCK_RE.search(
                    model.sf.comment_at_or_above(fn.lineno)
                )
                if m is not None:
                    held0 = model.held_closure(m.group(1))
                    # the annotated lock participates in the lock-order
                    # graph exactly like a lexical `with` — a lock
                    # acquired inside a holds-lock method is an edge
                    order0 = [model.canonical(m.group(1))]
                self._visit(
                    fn.body, model, models, held0, order0, edges,
                    acquires, findings,
                )

        findings.extend(self._find_cycles(edges))
        return findings

    # -- access + acquisition walk ------------------------------------

    def _visit(
        self,
        body,
        model: _ClassModel,
        models: Dict[str, _ClassModel],
        held: Set[str],
        held_order: List[str],
        edges,
        acquires,
        findings,
    ) -> None:
        for node in body:
            self._visit_node(
                node, model, models, held, held_order, edges, acquires,
                findings,
            )

    def _visit_node(
        self, node, model, models, held, held_order, edges, acquires,
        findings,
    ) -> None:
        sf = model.sf
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested defs may escape (thread targets, callbacks): they
            # inherit NO held locks — unless annotated holds-lock
            inner: Set[str] = set()
            inner_order: List[str] = []
            m = HOLDS_LOCK_RE.search(sf.comment_at_or_above(node.lineno))
            if m is not None:
                inner = model.held_closure(m.group(1))
                inner_order = [model.canonical(m.group(1))]
            self._visit(
                node.body, model, models, inner, inner_order, edges,
                acquires, findings,
            )
            return
        if isinstance(node, ast.Lambda):
            # wait_for predicates & sort keys run in the calling context
            self._visit_node(
                node.body, model, models, held, held_order, edges,
                acquires, findings,
            )
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            gained: List[str] = []
            # acquisition order within one multi-item `with self._a,
            # self._b:` counts too — item k is acquired holding items
            # 0..k-1, so the intra-statement edges must be recorded
            cur_order = list(held_order)
            for item in node.items:
                attr = _self_attr(item.context_expr)
                if attr is not None and attr in model.locks:
                    canon = model.canonical(attr)
                    for h in cur_order:
                        edge = (f"{model.name}.{h}", f"{model.name}.{canon}")
                        if edge[0] != edge[1]:
                            edges.setdefault(edge, (sf.rel, item.context_expr.lineno))
                    gained.append(canon)
                    if canon not in cur_order:
                        cur_order.append(canon)
                else:
                    self._visit_node(
                        item.context_expr, model, models, held,
                        held_order, edges, acquires, findings,
                    )
                if item.optional_vars is not None:
                    self._visit_node(
                        item.optional_vars, model, models, held,
                        held_order, edges, acquires, findings,
                    )
            new_held = set(held)
            new_order = list(held_order)
            for g in gained:
                for name in model.held_closure(g):
                    if name not in new_held:
                        new_held.add(name)
                if g not in new_order:
                    new_order.append(g)
            self._visit(
                node.body, model, models, new_held, new_order, edges,
                acquires, findings,
            )
            return
        if isinstance(node, ast.Call):
            self._resolve_call_edges(
                node, model, models, held_order, edges, acquires
            )
        if isinstance(node, ast.Attribute):
            attr = _self_attr(node)
            if attr is not None and attr in model.guards:
                lock = model.guards[attr][0]
                if not (model.held_closure(lock) & held):
                    kind = (
                        "written" if isinstance(node.ctx, (ast.Store, ast.Del))
                        else "read"
                    )
                    findings.append(
                        Finding(
                            sf.rel,
                            node.lineno,
                            "guarded-access",
                            f"{model.name}.{attr} (guarded-by: {lock}) "
                            f"{kind} without holding self.{lock}",
                        )
                    )
        for child in ast.iter_child_nodes(node):
            self._visit_node(
                child, model, models, held, held_order, edges, acquires,
                findings,
            )

    def _resolve_call_edges(
        self, node: ast.Call, model, models, held_order, edges, acquires
    ) -> None:
        """One level of call resolution under held locks: self.m(),
        self.attr.m() for typed attrs, and ClassName() constructors."""
        if not held_order:
            return
        fn = node.func
        target: Set[str] = set()
        callee_file = model.sf.rel
        if isinstance(fn, ast.Attribute):
            owner = fn.value
            if isinstance(owner, ast.Name) and owner.id in ("self", "cls"):
                target = acquires.get(fn.attr, set())
                target = {f"{model.name}.{t}" for t in target}
            else:
                attr = _self_attr(owner)
                if attr is not None and attr in model.attr_types:
                    other = models.get(model.attr_types[attr])
                    if other is not None:
                        ofn = other.methods.get(fn.attr)
                        if ofn is not None:
                            callee_file = other.sf.rel
                            target = {
                                f"{other.name}.{t}"
                                for t in _direct_acquires(ofn, other)
                            }
        elif isinstance(fn, ast.Name) and fn.id in models:
            other = models[fn.id]
            ofn = other.methods.get("__init__")
            if ofn is not None:
                callee_file = other.sf.rel
                target = {
                    f"{other.name}.{t}"
                    for t in _direct_acquires(ofn, other)
                }
        if not target:
            return
        for h in held_order:
            src = f"{model.name}.{h}"
            for dst in target:
                if src != dst:
                    edges.setdefault(
                        (src, dst), (model.sf.rel, node.lineno)
                    )
        # note: callee_file kept for possible richer reporting
        del callee_file

    # -- cycle detection ----------------------------------------------

    def _find_cycles(self, edges) -> List[Finding]:
        graph: Dict[str, List[str]] = {}
        for (a, b) in edges:
            graph.setdefault(a, []).append(b)
        findings: List[Finding] = []
        seen_cycles: Set[frozenset] = set()
        WHITE, GRAY, BLACK = 0, 1, 2
        color: Dict[str, int] = {}

        def dfs(n: str, stack: List[str]):
            color[n] = GRAY
            stack.append(n)
            for m in graph.get(n, ()):
                if color.get(m, WHITE) == WHITE:
                    dfs(m, stack)
                elif color.get(m) == GRAY:
                    cycle = stack[stack.index(m):] + [m]
                    key = frozenset(cycle)
                    if key not in seen_cycles:
                        seen_cycles.add(key)
                        path, line = edges[(stack[-1], m)]
                        findings.append(
                            Finding(
                                path,
                                line,
                                "lock-order",
                                "potential deadlock: lock-order cycle "
                                + " -> ".join(cycle),
                            )
                        )
            stack.pop()
            color[n] = BLACK

        for n in sorted(graph):
            if color.get(n, WHITE) == WHITE:
                dfs(n, [])
        return findings
