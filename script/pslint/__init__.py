"""pslint: the repo's multi-pass static-analysis framework.

One AST-based engine (file discovery, per-rule scoping, suppressions
with mandatory reasons, findings report, exit codes) shared by every
checked-in analysis pass:

- ``locks``     — lock-discipline race detector: ``# guarded-by:``
                  annotations on shared mutable attributes, flagged when
                  read/written outside ``with self.<lock>:``, plus a
                  cross-class lock-order graph with deadlock-cycle
                  detection (doc/STATIC_ANALYSIS.md).
- ``threads``   — thread-lifecycle pass: every ``threading.Thread``
                  spawn site must have an owner that joins it.
- ``jit-purity``— Python side effects inside jitted data-plane
                  functions in ``ops/`` (telemetry, host numpy, clocks,
                  nonlocal mutation) run at TRACE time only and then
                  silently vanish from the compiled step.
- ``donation``  — the donation lint (script/donation_lint.py) as an
                  engine pass: every data-plane jit declares
                  ``donate_argnums`` or a ``# no-donate:`` reason.
- ``metrics``   — the telemetry-catalog lint (script/metrics_lint.py)
                  as an engine pass: naming, duplicates, exposition.

Pure ``ast`` + ``tokenize`` for the static passes — no jax import, fast
enough for tier-1 (tests/test_pslint.py runs the whole suite against
the repo). Run via ``make pslint`` or ``python script/pslint/cli.py``.
"""

from .engine import Engine, Finding, Rule, SourceFile, default_rules  # noqa: F401
