"""Thread-affinity pass (rule ``thread-affinity``).

The stateless-or-feeder rule (PR 3, ROADMAP invariant): mutable state
lives on exactly one owner thread; other threads only FEED it through
locked handoff points. ``ContinuousBatcher._check_owner`` enforces this
dynamically for one class — this pass makes the rule static for every
class that declares its owner:

    # owner-thread: scheduler
    class ContinuousBatcher:
        ...

on the ``class`` line (all methods owned) or on an individual ``def``
line (that method only; a method-level annotation overrides the class
level, and the special owner ``any`` marks a method as intentionally
thread-safe/shared, exempting it).

A finding is an owned method reachable from **two or more distinct
thread entry points** without a lock: the method's state can be touched
concurrently, which is exactly what single-owner design forbids. Entry
points are:

- ``Thread(target=X)`` spawn sites — identified by the thread's
  ``name=`` constant when given, else by spawn file:line (two spawns of
  the same target ARE two entries: that target runs concurrently with
  itself);
- HTTP handler methods (``do_GET``-shaped methods of ``*Handler``
  classes) — the stdlib server runs each on its service thread.

"Reachable" is the call graph the symbol table can see, **two call
levels deep** from the entry function: ``self.m()``, methods of typed
attributes (``self.x = ClassName(...)``), and same-file module
functions. Deeper chains, callbacks, and dynamic dispatch are
invisible — the pass under-approximates reachability, never
over-approximates an exemption.

The lock escape: an owned method that takes any of its class's locks
(lexical ``with self.<lock>:``) or declares ``# holds-lock:`` is a
feeder handoff, not a violation. ``__init__``/``__del__`` are exempt
(construction happens-before sharing).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .engine import (
    ClassModel,
    Finding,
    Rule,
    SourceFile,
    callee_chain,
    self_attr,
    walk_package,
)

#: methods the stdlib HTTP machinery invokes on a service thread
_HTTP_METHOD_RE = "do_"


def _thread_ctor(call: ast.Call) -> bool:
    return callee_chain(call)[-1] == "Thread"


class ThreadAffinityRule(Rule):
    name = "thread-affinity"
    version = "1"

    def __init__(self, scope: Optional[Sequence[str]] = None):
        self.scope = tuple(scope) if scope is not None else None

    def paths(self, root: str) -> Sequence[str]:
        if self.scope is not None:
            return self.scope
        return walk_package(root)

    def check(self, files: Dict[str, SourceFile], root: str) -> List[Finding]:
        project = self.get_project(files)
        index = {
            name: model
            for name, model in project.class_index().items()
            if model is not None and model.sf.rel in files
        }
        # module-level functions per file (for Thread(target=plain_name))
        module_funcs: Dict[str, Dict[str, ast.AST]] = {}
        for rel, sf in files.items():
            module_funcs[rel] = {
                n.name: n
                for n in sf.tree.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            }

        # -- entry points: (entry_id, model|None, fn, rel) -------------
        entries: List[Tuple[str, Optional[ClassModel], ast.AST, str]] = []
        for rel, sf in files.items():
            models_here = {m.name: m for m in project.classes(rel)}
            parents = None
            for node in ast.walk(sf.tree):
                if not (isinstance(node, ast.Call) and _thread_ctor(node)):
                    continue
                target = None
                tname = None
                for kw in node.keywords:
                    if kw.arg == "target":
                        target = kw.value
                    elif kw.arg == "name" and isinstance(
                        kw.value, ast.Constant
                    ):
                        tname = str(kw.value.value)
                if target is None and node.args:
                    target = node.args[1] if len(node.args) > 1 else None
                if target is None:
                    continue
                entry_id = tname or f"{rel}:{node.lineno}"
                if parents is None:
                    parents = sf.parents()
                resolved = self._resolve_target(
                    target, node, parents, models_here, index,
                    module_funcs.get(rel, {}),
                )
                if resolved is not None:
                    model, fn = resolved
                    entries.append((entry_id, model, fn, rel))
            # HTTP handlers: each do_* method is its own service-thread
            # entry into the process
            for model in models_here.values():
                if not model.name.endswith("Handler"):
                    continue
                for mname, fn in model.methods.items():
                    if mname.startswith(_HTTP_METHOD_RE):
                        entries.append(
                            (f"http:{model.name}.{mname}", model, fn, rel)
                        )

        # -- reachability, two call levels deep ------------------------
        # owned (class, method) -> {entry ids that reach it}
        reached: Dict[Tuple[str, str], Set[str]] = {}
        sites: Dict[Tuple[str, str], Tuple[str, int]] = {}
        for entry_id, model, fn, rel in entries:
            seen: Set[int] = set()
            frontier = [(model, fn, rel)]
            for _depth in range(3):  # entry fn + two levels of callees
                nxt: List[Tuple[Optional[ClassModel], ast.AST, str]] = []
                for cmodel, cfn, crel in frontier:
                    if id(cfn) in seen:
                        continue
                    seen.add(id(cfn))
                    self._note(cmodel, cfn, entry_id, reached, sites)
                    nxt.extend(
                        self._callees(cmodel, cfn, crel, index, module_funcs)
                    )
                frontier = nxt
        findings: List[Finding] = []
        for (cname, mname), ids in sorted(reached.items()):
            if len(ids) < 2:
                continue
            path, line = sites[(cname, mname)]
            shown = ", ".join(sorted(ids))
            findings.append(
                Finding(
                    path,
                    line,
                    "thread-affinity",
                    f"{cname}.{mname} is owner-thread state but is "
                    f"reachable from {len(ids)} thread entry points "
                    f"({shown}) without a lock; add a lock/holds-lock, "
                    "route through a locked feeder, or annotate the "
                    "method '# owner-thread: any' if it is thread-safe",
                )
            )
        return findings

    # -- helpers ------------------------------------------------------

    def _note(self, model, fn, entry_id, reached, sites) -> None:
        if model is None or not isinstance(
            fn, (ast.FunctionDef, ast.AsyncFunctionDef)
        ):
            return
        owner = model.method_owner.get(fn.name, model.owner_thread)
        if owner is None or owner == "any":
            return
        if fn.name in ("__init__", "__del__"):
            return
        if model.acquires_any_lock(fn):
            return
        key = (model.name, fn.name)
        reached.setdefault(key, set()).add(entry_id)
        sites[key] = (model.sf.rel, fn.lineno)

    def _resolve_target(
        self, target, call, parents, models_here, index, funcs
    ) -> Optional[Tuple[Optional[ClassModel], ast.AST]]:
        """Thread target expr -> (owning class model | None, def)."""
        attr = self_attr(target)
        if attr is not None:
            # enclosing class of the spawn site owns self
            node = call
            while node in parents:
                node = parents[node]
                if isinstance(node, ast.ClassDef):
                    model = models_here.get(node.name) or index.get(node.name)
                    if model is not None and attr in model.methods:
                        return model, model.methods[attr]
                    return None
            return None
        if isinstance(target, ast.Name) and target.id in funcs:
            return None, funcs[target.id]
        if isinstance(target, ast.Attribute) and isinstance(
            target.value, ast.Name
        ):
            # obj.method where obj's class is identifiable by unique name
            for model in index.values():
                if model.name == target.value.id:
                    fn = model.methods.get(target.attr)
                    if fn is not None:
                        return model, fn
        return None

    def _callees(
        self, model, fn, rel, index, module_funcs
    ) -> List[Tuple[Optional[ClassModel], ast.AST, str]]:
        out: List[Tuple[Optional[ClassModel], ast.AST, str]] = []
        for call in ast.walk(fn):
            if not isinstance(call, ast.Call):
                continue
            f = call.func
            if isinstance(f, ast.Attribute):
                owner = f.value
                if (
                    isinstance(owner, ast.Name)
                    and owner.id in ("self", "cls")
                    and model is not None
                ):
                    m = model.methods.get(f.attr)
                    if m is not None:
                        out.append((model, m, model.sf.rel))
                    continue
                oattr = self_attr(owner)
                if (
                    oattr is not None
                    and model is not None
                    and oattr in model.attr_types
                ):
                    other = index.get(model.attr_types[oattr])
                    if other is not None:
                        m = other.methods.get(f.attr)
                        if m is not None:
                            out.append((other, m, other.sf.rel))
            elif isinstance(f, ast.Name):
                funcs = module_funcs.get(rel, {})
                if f.id in funcs:
                    out.append((None, funcs[f.id], rel))
        return out
