"""Metrics pass (rule ``metrics``): script/metrics_lint.py refitted as
an engine pass.

Unlike the static passes this one is DYNAMIC — it instantiates the
telemetry catalog (parameter_server_tpu.telemetry, no jax import)
against a fresh registry and validates names, duplicates and the text
exposition. The logic stays in ``script/metrics_lint.py`` (tests and
the ``make metrics-lint`` alias keep using it directly); this pass
loads it by file path and reports its problems as engine findings.

Catalog problems have no single source line, so findings anchor at
line 1 of the instrument catalog module.
"""

from __future__ import annotations

from typing import Dict, List, Sequence

from .donation import _load_sibling
from .engine import Finding, Rule, SourceFile

_CATALOG = "parameter_server_tpu/telemetry/instruments.py"


class MetricsRule(Rule):
    name = "metrics"

    def paths(self, root: str) -> Sequence[str]:
        return ()

    def check(self, files: Dict[str, SourceFile], root: str) -> List[Finding]:
        lint = _load_sibling("metrics_lint")
        return [
            Finding(_CATALOG, 1, self.name, problem)
            for problem in lint.lint(root)
        ]
