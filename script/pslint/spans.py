"""Span-discipline pass (rule ``span-with``, pass ``spans``).

``telemetry.spans.span(...)`` is a context manager: called bare, it
builds a generator that never runs — the block is silently untimed and,
worse, a *partially* entered span (``ctx = span(...)`` stored for
later) can die with its owner and leave an open-ended track that
corrupts the timeline (the PR-1 span-leak hazard; the dynamic half of
the fix is the pool's ``abandoned`` terminator in
utils/concurrent.OrderedStagePool). This pass enforces the static half:
every ``span(...)`` / ``<alias>.span(...)`` call must be the context
expression of a ``with`` statement (or an ``ExitStack.enter_context``
argument, which gives it an owner with the same exit guarantee).

Matched call shapes — chosen so regex-``Match.span()`` and other
unrelated ``.span`` attributes never trip the rule:

- bare ``span(...)`` (the ``from telemetry import span`` idiom);
- ``<mod>.span(...)`` where ``<mod>`` is a name containing "span" or
  "tracer" (``spans.span``, ``telemetry_spans.span``, ``tracer.span``).

Genuinely deferred spans declare their owner:

    # pslint: disable=span-with — <who enters/closes it and why>
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence

from .engine import Finding, Rule, SourceFile, walk_package

_ALIAS_HINTS = ("span", "tracer")


def _is_span_call(node: ast.Call) -> bool:
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id == "span"
    if isinstance(fn, ast.Attribute) and fn.attr == "span":
        base = fn.value
        if isinstance(base, ast.Name):
            return any(h in base.id.lower() for h in _ALIAS_HINTS)
    return False


class SpanDisciplineRule(Rule):
    name = "spans"
    version = "2"
    per_file = True  # no cross-file state: content-hash cacheable

    def __init__(self, scope: Optional[Sequence[str]] = None):
        self.scope = scope

    def paths(self, root: str) -> Sequence[str]:
        if self.scope is not None:
            return self.scope
        # bench.py lives at the repo root but is a first-class span
        # call site (the attribution section's stage spans)
        return list(walk_package(root)) + ["bench.py"]

    def check(self, files: Dict[str, SourceFile], root: str) -> List[Finding]:
        findings: List[Finding] = []
        for sf in files.values():
            findings.extend(self._check_file(sf))
        return findings

    def _check_file(self, sf: SourceFile) -> List[Finding]:
        findings: List[Finding] = []
        # the defining module itself (telemetry/spans.py) declares the
        # contextmanager; its internals are not call sites
        if sf.rel.endswith("telemetry/spans.py"):
            return findings
        parents = sf.parents()  # engine-shared parent chain
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.Call) and _is_span_call(node)):
                continue
            parent = parents.get(node)
            # `with span(...):` / `with a, span(...) as s:` — the call
            # is a withitem's context expression
            if isinstance(parent, ast.withitem) and parent.context_expr is node:
                continue
            # `stack.enter_context(span(...))` — the stack owns exit
            if (
                isinstance(parent, ast.Call)
                and isinstance(parent.func, ast.Attribute)
                and parent.func.attr == "enter_context"
                and node in parent.args
            ):
                continue
            findings.append(
                Finding(
                    sf.rel,
                    node.lineno,
                    "span-with",
                    "tracer span(...) used outside a `with` statement — "
                    "the block is untimed and the span can leak "
                    "open-ended into the timeline; write `with "
                    "span(...):` (or enter_context), or disable with "
                    "a reason",
                )
            )
        return findings
