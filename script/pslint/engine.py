"""pslint engine: shared machinery for every analysis pass.

Responsibilities (and nothing else — rules own their logic):

- **file discovery + parsing**: each scoped file is read, tokenized and
  ast-parsed exactly ONCE per run, then shared across passes;
- **project symbol table**: a cross-file view built lazily over every
  scoped file — per-class models (locks, guards, attribute types,
  methods, owner-thread annotations), jit-target names, a donation map
  (which callables consume which positional buffers, propagated one
  wrapper level), and one-level call resolution. Passes that reason
  across files (locks, use-after-donate, thread-affinity) consume THIS
  table instead of growing private ones;
- **suppressions**: ``# pslint: disable=<rule>[,<rule>] — <reason>``
  on the flagged line (or a standalone comment on the line above)
  silences that rule there. The reason is MANDATORY — a disable
  without one is itself a finding (rule ``suppression``) that cannot
  be suppressed;
- **incremental cache**: per-file passes (``Rule.per_file = True``)
  cache their findings keyed by the file's CONTENT HASH (+ engine and
  rule version salts), so an unchanged file never re-analyzes and an
  edited file always does — a stale entry can never hide a finding
  because the key is the content itself. Cross-file passes are never
  cached: one file's edit can change another file's findings, which is
  exactly the staleness a per-file key cannot express;
- **report + exit codes**: findings print one per line as
  ``path:line rule message`` (editor-clickable), exit 0 clean / 1
  findings / 2 internal error. Per-pass wall-clock lands in
  ``Engine.timings`` (``cli.py --timings``).

The engine imports only the standard library — no jax, no repo
modules — so the static passes stay import-safe and fast. Dynamic
passes (metrics) do their own guarded imports inside ``check``.
"""

from __future__ import annotations

import ast
import hashlib
import io
import json
import os
import re
import time
import tokenize
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

#: cache salt — bump whenever engine or pass semantics change so a
#: stale cache from an older checkout cannot satisfy a newer rule
PSLINT_VERSION = "2"


@dataclass(frozen=True)
class Finding:
    """One problem at one location. ``rule`` is the suppression key."""

    path: str  # repo-relative, forward slashes
    line: int
    rule: str
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line} {self.rule} {self.message}"


# `# pslint: disable=rule-a,rule-b — reason` (em/en dash, `--`, or `-`)
_SUPPRESS_RE = re.compile(
    r"#\s*pslint:\s*disable=\s*"
    r"(?P<rules>[a-z][a-z0-9-]*(?:\s*,\s*[a-z][a-z0-9-]*)*)"
    r"(?:\s*(?:—|–|--|-)\s*(?P<reason>.*))?$"
)

_SUPPRESSION_RULE = "suppression"

# shared annotation grammar (doc/STATIC_ANALYSIS.md):
GUARDED_BY_RE = re.compile(r"guarded-by:\s*([A-Za-z_][A-Za-z0-9_]*)")
HOLDS_LOCK_RE = re.compile(r"holds-lock:\s*([A-Za-z_][A-Za-z0-9_]*)")
OWNER_THREAD_RE = re.compile(r"owner-thread:\s*([A-Za-z_][A-Za-z0-9_.-]*)")
DONATES_RE = re.compile(r"#\s*donates:\s*([0-9]+(?:\s*,\s*[0-9]+)*)")
BIT_IDENTICAL_RE = re.compile(r"#\s*bit-identical\b")

LOCK_FACTORIES = {"Lock", "RLock", "Condition"}


class SourceFile:
    """One scoped file, parsed once and shared by every pass."""

    def __init__(self, root: str, rel: str):
        self.root = root
        self.rel = rel.replace(os.sep, "/")
        self.path = os.path.join(root, rel)
        with open(self.path, "r", encoding="utf-8") as f:
            self.text = f.read()
        self.sha = hashlib.sha256(self.text.encode("utf-8")).hexdigest()
        self.lines = self.text.splitlines()
        self.tree = ast.parse(self.text, filename=self.rel)
        # line -> raw comment text (tokenize keeps comments ast drops)
        self.comments: Dict[int, str] = {}
        try:
            for tok in tokenize.generate_tokens(io.StringIO(self.text).readline):
                if tok.type == tokenize.COMMENT:
                    self.comments[tok.start[0]] = tok.string
        except tokenize.TokenError:
            pass  # a truncated final line still lints on the ast
        # line -> (set of suppressed rules, has_reason)
        self.suppressions: Dict[int, Tuple[set, bool]] = {}
        for line, comment in self.comments.items():
            m = _SUPPRESS_RE.search(comment)
            if m is None:
                continue
            rules = {r.strip() for r in m.group("rules").split(",")}
            reason = (m.group("reason") or "").strip()
            self.suppressions[line] = (rules, bool(reason))
        self._parents: Optional[Dict[ast.AST, ast.AST]] = None

    def parents(self) -> Dict[ast.AST, ast.AST]:
        """child AST node -> parent, built once and shared (threads,
        spans and the dataflow passes all need the parent chain)."""
        if self._parents is None:
            self._parents = {}
            for node in ast.walk(self.tree):
                for child in ast.iter_child_nodes(node):
                    self._parents[child] = node
        return self._parents

    def comment_at_or_above(self, line: int) -> str:
        """Trailing comment on ``line`` plus any comment line directly
        above — the two places annotations may sit."""
        parts = []
        above = self.comments.get(line - 1)
        if above is not None and self.lines[line - 2].lstrip().startswith("#"):
            parts.append(above)
        here = self.comments.get(line)
        if here is not None:
            parts.append(here)
        return "\n".join(parts)

    def is_suppressed(self, rule: str, line: int) -> bool:
        """A finding is silenced by a REASONED disable on its own line
        or on a standalone comment line directly above it."""
        for ln in (line, line - 1):
            entry = self.suppressions.get(ln)
            if entry is None:
                continue
            if ln == line - 1 and not self.lines[ln - 1].lstrip().startswith("#"):
                continue  # trailing comment of the PREVIOUS statement
            rules, has_reason = entry
            if rule in rules and has_reason:
                return True
        return False


# -- symbol table -----------------------------------------------------


def self_attr(node: ast.AST) -> Optional[str]:
    """``self.X`` or ``cls.X`` -> ``X`` (instance and classmethod forms
    address the same per-class state)."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id in ("self", "cls")
    ):
        return node.attr
    return None


def _lock_factory_call(node: ast.AST) -> Optional[Tuple[str, Optional[str]]]:
    """``threading.Lock()`` etc -> (factory, wrapped_attr|None)."""
    if not isinstance(node, ast.Call):
        return None
    fn = node.func
    name = None
    if isinstance(fn, ast.Attribute) and fn.attr in LOCK_FACTORIES:
        name = fn.attr
    elif isinstance(fn, ast.Name) and fn.id in LOCK_FACTORIES:
        name = fn.id
    if name is None:
        return None
    wrapped = None
    if name == "Condition" and node.args:
        wrapped = self_attr(node.args[0])
    return name, wrapped


class ClassModel:
    """Per-class facts shared by the locks / affinity / dataflow
    passes: locks, aliases, guards, attribute types, methods, and the
    single-owner annotations."""

    def __init__(self, name: str, sf: SourceFile, lineno: int = 0):
        self.name = name
        self.sf = sf
        self.lineno = lineno
        self.locks: Set[str] = set()
        self.alias: Dict[str, str] = {}  # condition attr -> wrapped lock
        self.guards: Dict[str, Tuple[str, int]] = {}  # attr -> (lock, line)
        self.attr_types: Dict[str, str] = {}  # attr -> class name
        self.methods: Dict[str, ast.FunctionDef] = {}
        self.owner_thread: Optional[str] = None  # class-level owner
        self.method_owner: Dict[str, str] = {}  # per-method owner

    def canonical(self, lock: str) -> str:
        """Condition-over-lock aliases collapse to the wrapped lock."""
        return self.alias.get(lock, lock)

    def held_closure(self, lock: str) -> Set[str]:
        """Every lock name satisfied by acquiring ``lock``."""
        out = {lock}
        wrapped = self.alias.get(lock)
        if wrapped is not None:
            out.add(wrapped)
        for cond, target in self.alias.items():
            if target == lock:
                out.add(cond)
        return out

    def acquires_any_lock(self, fn: ast.AST) -> bool:
        """Does ``fn`` lexically take any of this class's locks (or
        declare holds-lock)? The affinity pass's "has a lock
        annotation" escape."""
        m = HOLDS_LOCK_RE.search(self.sf.comment_at_or_above(fn.lineno))
        if m is not None:
            return True
        return bool(direct_acquires(fn, self))


def direct_acquires(fn: ast.AST, model: ClassModel) -> Set[str]:
    """Lock attrs this function acquires via ``with self.<L>:`` anywhere
    in its body (canonicalized; used for one-level call resolution)."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                attr = self_attr(item.context_expr)
                if attr is not None and attr in model.locks:
                    out.add(model.canonical(attr))
    return out


def collect_class(cls: ast.ClassDef, sf: SourceFile) -> ClassModel:
    model = ClassModel(cls.name, sf, cls.lineno)
    m = OWNER_THREAD_RE.search(sf.comment_at_or_above(cls.lineno))
    if m is not None:
        model.owner_thread = m.group(1)

    def scan_assign(target: ast.AST, value: Optional[ast.AST], line: int):
        attr = None
        if isinstance(target, ast.Name):  # class-level attribute
            attr = target.id
        else:
            attr = self_attr(target)
        if attr is None:
            return
        if value is not None:
            fac = _lock_factory_call(value)
            if fac is not None:
                model.locks.add(attr)
                if fac[1] is not None:
                    model.alias[attr] = fac[1]
            elif isinstance(value, ast.Call) and isinstance(
                value.func, ast.Name
            ):
                model.attr_types.setdefault(attr, value.func.id)
        g = GUARDED_BY_RE.search(sf.comment_at_or_above(line))
        if g is not None:
            model.guards.setdefault(attr, (g.group(1), line))

    for node in cls.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            model.methods[node.name] = node
            mo = OWNER_THREAD_RE.search(sf.comment_at_or_above(node.lineno))
            if mo is not None:
                model.method_owner[node.name] = mo.group(1)
            for stmt in ast.walk(node):
                if isinstance(stmt, ast.Assign):
                    for t in stmt.targets:
                        scan_assign(t, stmt.value, stmt.lineno)
                elif isinstance(stmt, ast.AnnAssign):
                    scan_assign(stmt.target, stmt.value, stmt.lineno)
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                scan_assign(t, node.value, node.lineno)
        elif isinstance(node, ast.AnnAssign):
            scan_assign(node.target, node.value, node.lineno)
    return model


def callee_chain(call: ast.Call) -> Tuple[str, ...]:
    """Dotted callee parts: ``kv_ops.push_donated(...)`` ->
    ("kv_ops", "push_donated"); unresolvable owners become "?"."""
    parts: List[str] = []
    node: ast.AST = call.func
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    else:
        parts.append("?")
    return tuple(reversed(parts))


def _is_jit_ref(node: ast.AST) -> bool:
    if isinstance(node, ast.Attribute) and node.attr == "jit":
        return True
    return isinstance(node, ast.Name) and node.id == "jit"


def _is_jit_partial(node: ast.AST) -> bool:
    """``(functools.)partial(jax.jit, ...)``."""
    if not isinstance(node, ast.Call):
        return False
    fn = node.func
    is_partial = (
        isinstance(fn, ast.Attribute) and fn.attr == "partial"
        or isinstance(fn, ast.Name) and fn.id == "partial"
    )
    return is_partial and bool(node.args) and _is_jit_ref(node.args[0])


def jit_target_names(tree: ast.Module) -> Set[str]:
    """Names of module-level functions that are jitted by reference:
    ``jit(f)``, ``partial(jax.jit, ...)(f)``."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if _is_jit_ref(node.func) or _is_jit_partial(node.func):
            for arg in node.args:
                if isinstance(arg, ast.Name):
                    names.add(arg.id)
    return names


def _donate_positions(expr: ast.AST) -> Tuple[int, ...]:
    """Donated positions from any ``donate_argnums=`` keyword found
    inside ``expr`` (jit call, partial(jit, ...), instrument wrapper)."""
    out: Set[int] = set()
    for node in ast.walk(expr):
        if not isinstance(node, ast.Call):
            continue
        for kw in node.keywords:
            if kw.arg != "donate_argnums":
                continue
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                out.add(v.value)
            elif isinstance(v, (ast.Tuple, ast.List)):
                for el in v.elts:
                    if isinstance(el, ast.Constant) and isinstance(el.value, int):
                        out.add(el.value)
    return tuple(sorted(out))


#: a callee whose terminal name matches this donates its first
#: positional argument even when the definition is out of scope — the
#: ``push_donated`` / ``kv_push_pull_donated`` wrapper naming shape
DONATED_NAME_RE = re.compile(r"(^|_)donated$")


class Project:
    """Cross-file symbol table, built lazily over every file the run
    loads. One instance per Engine.run; passes reach it via
    ``self.project`` (falling back to a private build when a rule is
    driven directly in tests)."""

    def __init__(self) -> None:
        self._files: Dict[str, SourceFile] = {}
        self._classes: Dict[str, List[ClassModel]] = {}
        self._jit_names: Dict[str, Set[str]] = {}
        self._donating: Optional[Dict[str, Tuple[int, ...]]] = None
        self._index: Optional[Dict[str, Optional[ClassModel]]] = None

    @classmethod
    def from_files(cls, files: Dict[str, "SourceFile"]) -> "Project":
        p = cls()
        for sf in files.values():
            p.add(sf)
        return p

    def add(self, sf: SourceFile) -> None:
        if sf.rel not in self._files:
            self._files[sf.rel] = sf
            self._donating = None  # new file may add donation facts
            self._index = None

    def files(self) -> Dict[str, SourceFile]:
        return self._files

    def classes(self, rel: str) -> List[ClassModel]:
        if rel not in self._classes:
            sf = self._files.get(rel)
            models: List[ClassModel] = []
            if sf is not None:
                for node in sf.tree.body:
                    if isinstance(node, ast.ClassDef):
                        models.append(collect_class(node, sf))
            self._classes[rel] = models
        return self._classes[rel]

    def class_index(self) -> Dict[str, Optional[ClassModel]]:
        """name -> model, or None when two files reuse the name
        (ambiguous names resolve to NO edges rather than wrong-class
        edges — conservative, same policy as the locks pass)."""
        if self._index is None:
            index: Dict[str, Optional[ClassModel]] = {}
            for rel in sorted(self._files):
                for model in self.classes(rel):
                    if model.name in index:
                        index[model.name] = None
                    else:
                        index[model.name] = model
            self._index = index
        return self._index

    def jit_targets(self, rel: str) -> Set[str]:
        if rel not in self._jit_names:
            sf = self._files.get(rel)
            self._jit_names[rel] = (
                jit_target_names(sf.tree) if sf is not None else set()
            )
        return self._jit_names[rel]

    # -- donation map -------------------------------------------------

    def donating(self) -> Dict[str, Tuple[int, ...]]:
        """Terminal callable name -> donated positional indices
        (``self`` excluded for methods). Seeded from ``donate_argnums``
        declarations and ``# donates: <pos>`` def annotations, then
        propagated one wrapper level: a function that passes its own
        positional parameter at a donated position of a donating callee
        donates that parameter too.

        Only MODULE-LEVEL names (top-level defs/assigns and class
        methods/attributes) enter this map: cross-module calls resolve
        by terminal name, so a function-local ``fn = jax.jit(...,
        donate_argnums=...)`` must not poison every unrelated ``fn``
        in the project — locals are the use-after-donate pass's
        per-function problem (``seed_locals``). A surviving name
        collision between modules unions positions (over-approximate,
        escape-hatched)."""
        if self._donating is not None:
            return self._donating
        donating: Dict[str, Set[int]] = {}

        def note(name: str, positions: Iterable[int]) -> None:
            donating.setdefault(name, set()).update(positions)

        def scan_scope(body, sf: SourceFile) -> None:
            for node in body:
                if isinstance(node, ast.ClassDef):
                    scan_scope(node.body, sf)
                elif isinstance(node, ast.Assign):
                    pos = _donate_positions(node.value)
                    if pos:
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                note(t.id, pos)
                            else:
                                attr = self_attr(t)
                                if attr is not None:
                                    note(attr, pos)
                        # by-reference jit in the value ALSO donates the
                        # referenced function: f2 = jit(f, donate...)
                        for call in ast.walk(node.value):
                            if isinstance(call, ast.Call) and (
                                _is_jit_ref(call.func)
                                or _is_jit_partial(call.func)
                            ):
                                cpos = _donate_positions(call)
                                for arg in call.args:
                                    if isinstance(arg, ast.Name) and cpos:
                                        note(arg.id, cpos)
                elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    for dec in node.decorator_list:
                        pos = _donate_positions(dec)
                        if pos:
                            note(node.name, pos)
                    m = DONATES_RE.search(sf.comment_at_or_above(node.lineno))
                    if m is not None:
                        note(
                            node.name,
                            (int(x) for x in m.group(1).split(",")),
                        )
                elif isinstance(node, ast.Expr) and isinstance(
                    node.value, ast.Call
                ):
                    call = node.value
                    if _is_jit_ref(call.func) or _is_jit_partial(call.func):
                        # jit(f, donate_argnums=...) by reference
                        pos = _donate_positions(call)
                        if pos:
                            for arg in call.args:
                                if isinstance(arg, ast.Name):
                                    note(arg.id, pos)

        for sf in self._files.values():
            scan_scope(sf.tree.body, sf)

        def module_functions(sf: SourceFile):
            for node in sf.tree.body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield node
                elif isinstance(node, ast.ClassDef):
                    for sub in node.body:
                        if isinstance(
                            sub, (ast.FunctionDef, ast.AsyncFunctionDef)
                        ):
                            yield sub

        # one wrapper level, run to a short fixed point so a wrapper of
        # a wrapper still lands (module-level functions only)
        for _ in range(2):
            changed = False
            for sf in self._files.values():
                for fn in module_functions(sf):
                    params = [
                        a.arg
                        for a in fn.args.posonlyargs + fn.args.args
                        if a.arg not in ("self", "cls")
                    ]
                    if not params:
                        continue
                    for call in ast.walk(fn):
                        if not isinstance(call, ast.Call):
                            continue
                        name = callee_chain(call)[-1]
                        positions = donating.get(name)
                        if positions is None:
                            positions = (
                                {0} if DONATED_NAME_RE.search(name) else set()
                            )
                        for p in positions:
                            if p >= len(call.args):
                                continue
                            arg = call.args[p]
                            if (
                                isinstance(arg, ast.Name)
                                and arg.id in params
                            ):
                                i = params.index(arg.id)
                                cur = donating.setdefault(fn.name, set())
                                if i not in cur:
                                    cur.add(i)
                                    changed = True
            if not changed:
                break
        self._donating = {k: tuple(sorted(v)) for k, v in donating.items()}
        return self._donating


# -- rules ------------------------------------------------------------


class Rule:
    """Base class of an analysis pass.

    ``name`` selects the pass (``--rules``); ``paths(root)`` returns the
    repo-relative files it wants parsed; ``check(files, root)`` returns
    findings. ``files`` holds a SourceFile for every path that exists
    (missing scoped files are reported by the engine).

    ``per_file = True`` declares that ``check`` decomposes file-by-file
    with no cross-file state — the engine then runs it one file at a
    time and caches each file's findings by content hash. ``version``
    salts that cache: bump it when the rule's semantics change.
    ``self.project`` is the run's shared symbol table (set by the
    engine; rules driven directly fall back to building their own).
    """

    name: str = "base"
    version: str = "1"
    per_file: bool = False
    project: Optional[Project] = None

    def paths(self, root: str) -> Sequence[str]:
        return ()

    def check(self, files: Dict[str, SourceFile], root: str) -> List[Finding]:
        raise NotImplementedError

    def get_project(self, files: Dict[str, SourceFile]) -> Project:
        """The engine's shared project, or a private one over ``files``
        when the rule is driven outside an Engine run (tests)."""
        if self.project is not None:
            return self.project
        return Project.from_files(files)


def walk_package(root: str, package: str = "parameter_server_tpu") -> List[str]:
    """Every .py file under ``package`` (repo-relative, sorted)."""
    out: List[str] = []
    base = os.path.join(root, package)
    for dirpath, dirnames, filenames in os.walk(base):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                rel = os.path.relpath(os.path.join(dirpath, fn), root)
                out.append(rel.replace(os.sep, "/"))
    return sorted(out)


# -- incremental cache ------------------------------------------------


class LintCache:
    """Content-hash finding cache for per-file rules.

    Entries key on ``(rule, rule.version, engine version, file sha,
    path)`` — an edited file gets a NEW key, so a stale entry can never
    satisfy it (stale entries are dropped at save). The value is the
    rule's findings for that file BEFORE suppression filtering;
    suppressions re-apply from the current source every run, so editing
    only a suppression comment still changes the sha and recomputes."""

    def __init__(self, path: str):
        self.path = path
        self.entries: Dict[str, List[List]] = {}
        self.hits = 0
        self.misses = 0
        self._touched: Set[str] = set()
        try:
            with open(path, "r", encoding="utf-8") as f:
                data = json.load(f)
            if data.get("version") == PSLINT_VERSION:
                self.entries = data.get("entries", {})
        except (OSError, ValueError):
            pass  # absent/corrupt cache = cold run

    @staticmethod
    def _key(rule: Rule, sf: SourceFile) -> str:
        return f"{rule.name}:{rule.version}:{sf.sha}:{sf.rel}"

    def get(self, rule: Rule, sf: SourceFile) -> Optional[List[Finding]]:
        key = self._key(rule, sf)
        entry = self.entries.get(key)
        if entry is None:
            self.misses += 1
            return None
        self.hits += 1
        self._touched.add(key)
        return [Finding(p, ln, r, m) for p, ln, r, m in entry]

    def put(self, rule: Rule, sf: SourceFile, findings: List[Finding]) -> None:
        key = self._key(rule, sf)
        self.entries[key] = [
            [f.path, f.line, f.rule, f.message] for f in findings
        ]
        self._touched.add(key)

    def save(self) -> None:
        """Persist only the entries this run touched — entries for
        edited (old-sha) or deleted files age out instead of growing
        the cache forever."""
        data = {
            "version": PSLINT_VERSION,
            "entries": {k: self.entries[k] for k in sorted(self._touched)},
        }
        tmp = self.path + ".tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(data, f)
            os.replace(tmp, self.path)
        except OSError:
            pass  # read-only checkout: run uncached


# -- engine -----------------------------------------------------------


class Engine:
    def __init__(
        self,
        root: str,
        rules: Sequence[Rule],
        cache_path: Optional[str] = None,
    ):
        self.root = root
        self.rules = list(rules)
        self.cache = LintCache(cache_path) if cache_path else None
        self.timings: Dict[str, float] = {}  # pass name -> seconds
        #: per pass: files analyzed fresh vs served from cache
        self.stats: Dict[str, Dict[str, int]] = {}

    def run(self) -> Tuple[List[Finding], int]:
        """Returns (unsuppressed findings, suppressed count)."""
        cache: Dict[str, SourceFile] = {}
        findings: List[Finding] = []
        project = Project()

        def load(rel: str) -> Optional[SourceFile]:
            if rel not in cache:
                path = os.path.join(self.root, rel)
                if not os.path.exists(path):
                    findings.append(
                        Finding(rel, 1, "scope", "scoped file is missing")
                    )
                    cache[rel] = None  # type: ignore[assignment]
                    return None
                try:
                    cache[rel] = SourceFile(self.root, rel)
                except SyntaxError as e:
                    findings.append(
                        Finding(rel, e.lineno or 1, "parse", f"failed to parse: {e.msg}")
                    )
                    cache[rel] = None  # type: ignore[assignment]
            sf = cache[rel]
            if sf is not None:
                project.add(sf)
            return sf

        for rule in self.rules:
            t0 = time.perf_counter()
            rule.project = project
            files = {}
            for rel in rule.paths(self.root):
                sf = load(rel)
                if sf is not None:
                    files[rel] = sf
            stats = self.stats.setdefault(
                rule.name, {"analyzed": 0, "cached": 0}
            )
            if rule.per_file and self.cache is not None:
                for rel, sf in files.items():
                    hit = self.cache.get(rule, sf)
                    if hit is not None:
                        stats["cached"] += 1
                        findings.extend(hit)
                        continue
                    fresh = rule.check({rel: sf}, self.root)
                    self.cache.put(rule, sf, fresh)
                    stats["analyzed"] += 1
                    findings.extend(fresh)
            else:
                findings.extend(rule.check(files, self.root))
                stats["analyzed"] += len(files)
            self.timings[rule.name] = time.perf_counter() - t0

        # suppression hygiene over every file any pass touched: a
        # disable without a reason is a finding in its own right
        for sf in cache.values():
            if sf is None:
                continue
            for line, (rules, has_reason) in sorted(sf.suppressions.items()):
                if not has_reason:
                    findings.append(
                        Finding(
                            sf.rel,
                            line,
                            _SUPPRESSION_RULE,
                            "suppression without a reason: write "
                            "'# pslint: disable=<rule> — <reason>'",
                        )
                    )

        kept: List[Finding] = []
        suppressed = 0
        for f in findings:
            sf = cache.get(f.path)
            # the suppression rule itself is never suppressible —
            # otherwise a reasonless disable could silence the finding
            # that exists to demand its reason
            if (
                f.rule != _SUPPRESSION_RULE
                and sf is not None
                and sf.is_suppressed(f.rule, f.line)
            ):
                suppressed += 1
                continue
            kept.append(f)
        kept.sort(key=lambda f: (f.path, f.line, f.rule))
        if self.cache is not None:
            self.cache.save()
        return kept, suppressed


def default_rules(only: Optional[Iterable[str]] = None) -> List[Rule]:
    """The registered passes, optionally filtered by name."""
    from . import (
        affinity,
        artifacts,
        determinism,
        donate_flow,
        donation,
        jitpure,
        locks,
        metrics,
        spans,
        threads,
    )

    rules: List[Rule] = [
        locks.LockDisciplineRule(),
        threads.ThreadLifecycleRule(),
        jitpure.JitPurityRule(),
        donation.DonationRule(),
        metrics.MetricsRule(),
        spans.SpanDisciplineRule(),
        donate_flow.UseAfterDonateRule(),
        affinity.ThreadAffinityRule(),
        determinism.DeterminismRule(),
        artifacts.CrossArtifactRule(),
    ]
    if only is not None:
        wanted = set(only)
        known = {r.name for r in rules}
        unknown = wanted - known
        if unknown:
            raise ValueError(
                f"unknown rule(s) {sorted(unknown)}; known: {sorted(known)}"
            )
        rules = [r for r in rules if r.name in wanted]
    return rules
