"""pslint engine: shared machinery for every analysis pass.

Responsibilities (and nothing else — rules own their logic):

- **file discovery + parsing**: each scoped file is read, tokenized and
  ast-parsed exactly ONCE per run, then shared across passes;
- **suppressions**: ``# pslint: disable=<rule>[,<rule>] — <reason>``
  on the flagged line (or a standalone comment on the line above)
  silences that rule there. The reason is MANDATORY — a disable
  without one is itself a finding (rule ``suppression``) that cannot
  be suppressed;
- **report + exit codes**: findings print one per line as
  ``path:line rule message`` (editor-clickable), exit 0 clean / 1
  findings / 2 internal error.

The engine imports only the standard library — no jax, no repo
modules — so the static passes stay import-safe and fast. Dynamic
passes (metrics) do their own guarded imports inside ``check``.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Tuple


@dataclass(frozen=True)
class Finding:
    """One problem at one location. ``rule`` is the suppression key."""

    path: str  # repo-relative, forward slashes
    line: int
    rule: str
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line} {self.rule} {self.message}"


# `# pslint: disable=rule-a,rule-b — reason` (em/en dash, `--`, or `-`)
_SUPPRESS_RE = re.compile(
    r"#\s*pslint:\s*disable=\s*"
    r"(?P<rules>[a-z][a-z0-9-]*(?:\s*,\s*[a-z][a-z0-9-]*)*)"
    r"(?:\s*(?:—|–|--|-)\s*(?P<reason>.*))?$"
)

_SUPPRESSION_RULE = "suppression"


class SourceFile:
    """One scoped file, parsed once and shared by every pass."""

    def __init__(self, root: str, rel: str):
        self.root = root
        self.rel = rel.replace(os.sep, "/")
        self.path = os.path.join(root, rel)
        with open(self.path, "r", encoding="utf-8") as f:
            self.text = f.read()
        self.lines = self.text.splitlines()
        self.tree = ast.parse(self.text, filename=self.rel)
        # line -> raw comment text (tokenize keeps comments ast drops)
        self.comments: Dict[int, str] = {}
        try:
            for tok in tokenize.generate_tokens(io.StringIO(self.text).readline):
                if tok.type == tokenize.COMMENT:
                    self.comments[tok.start[0]] = tok.string
        except tokenize.TokenError:
            pass  # a truncated final line still lints on the ast
        # line -> (set of suppressed rules, has_reason)
        self.suppressions: Dict[int, Tuple[set, bool]] = {}
        for line, comment in self.comments.items():
            m = _SUPPRESS_RE.search(comment)
            if m is None:
                continue
            rules = {r.strip() for r in m.group("rules").split(",")}
            reason = (m.group("reason") or "").strip()
            self.suppressions[line] = (rules, bool(reason))

    def comment_at_or_above(self, line: int) -> str:
        """Trailing comment on ``line`` plus any comment line directly
        above — the two places annotations may sit."""
        parts = []
        above = self.comments.get(line - 1)
        if above is not None and self.lines[line - 2].lstrip().startswith("#"):
            parts.append(above)
        here = self.comments.get(line)
        if here is not None:
            parts.append(here)
        return "\n".join(parts)

    def is_suppressed(self, rule: str, line: int) -> bool:
        """A finding is silenced by a REASONED disable on its own line
        or on a standalone comment line directly above it."""
        for ln in (line, line - 1):
            entry = self.suppressions.get(ln)
            if entry is None:
                continue
            if ln == line - 1 and not self.lines[ln - 1].lstrip().startswith("#"):
                continue  # trailing comment of the PREVIOUS statement
            rules, has_reason = entry
            if rule in rules and has_reason:
                return True
        return False


class Rule:
    """Base class of an analysis pass.

    ``name`` selects the pass (``--rules``); ``paths(root)`` returns the
    repo-relative files it wants parsed; ``check(files, root)`` returns
    findings. ``files`` holds a SourceFile for every path that exists
    (missing scoped files are reported by the engine).
    """

    name: str = "base"

    def paths(self, root: str) -> Sequence[str]:
        return ()

    def check(self, files: Dict[str, SourceFile], root: str) -> List[Finding]:
        raise NotImplementedError


def walk_package(root: str, package: str = "parameter_server_tpu") -> List[str]:
    """Every .py file under ``package`` (repo-relative, sorted)."""
    out: List[str] = []
    base = os.path.join(root, package)
    for dirpath, dirnames, filenames in os.walk(base):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                rel = os.path.relpath(os.path.join(dirpath, fn), root)
                out.append(rel.replace(os.sep, "/"))
    return sorted(out)


class Engine:
    def __init__(self, root: str, rules: Sequence[Rule]):
        self.root = root
        self.rules = list(rules)

    def run(self) -> Tuple[List[Finding], int]:
        """Returns (unsuppressed findings, suppressed count)."""
        cache: Dict[str, SourceFile] = {}
        findings: List[Finding] = []

        def load(rel: str) -> Optional[SourceFile]:
            if rel not in cache:
                path = os.path.join(self.root, rel)
                if not os.path.exists(path):
                    findings.append(
                        Finding(rel, 1, "scope", "scoped file is missing")
                    )
                    cache[rel] = None  # type: ignore[assignment]
                    return None
                try:
                    cache[rel] = SourceFile(self.root, rel)
                except SyntaxError as e:
                    findings.append(
                        Finding(rel, e.lineno or 1, "parse", f"failed to parse: {e.msg}")
                    )
                    cache[rel] = None  # type: ignore[assignment]
            return cache[rel]

        for rule in self.rules:
            files = {}
            for rel in rule.paths(self.root):
                sf = load(rel)
                if sf is not None:
                    files[rel] = sf
            findings.extend(rule.check(files, self.root))

        # suppression hygiene over every file any pass touched: a
        # disable without a reason is a finding in its own right
        for sf in cache.values():
            if sf is None:
                continue
            for line, (rules, has_reason) in sorted(sf.suppressions.items()):
                if not has_reason:
                    findings.append(
                        Finding(
                            sf.rel,
                            line,
                            _SUPPRESSION_RULE,
                            "suppression without a reason: write "
                            "'# pslint: disable=<rule> — <reason>'",
                        )
                    )

        kept: List[Finding] = []
        suppressed = 0
        for f in findings:
            sf = cache.get(f.path)
            # the suppression rule itself is never suppressible —
            # otherwise a reasonless disable could silence the finding
            # that exists to demand its reason
            if (
                f.rule != _SUPPRESSION_RULE
                and sf is not None
                and sf.is_suppressed(f.rule, f.line)
            ):
                suppressed += 1
                continue
            kept.append(f)
        kept.sort(key=lambda f: (f.path, f.line, f.rule))
        return kept, suppressed


def default_rules(only: Optional[Iterable[str]] = None) -> List[Rule]:
    """The registered passes, optionally filtered by name."""
    from . import donation, jitpure, locks, metrics, spans, threads

    rules: List[Rule] = [
        locks.LockDisciplineRule(),
        threads.ThreadLifecycleRule(),
        jitpure.JitPurityRule(),
        donation.DonationRule(),
        metrics.MetricsRule(),
        spans.SpanDisciplineRule(),
    ]
    if only is not None:
        wanted = set(only)
        known = {r.name for r in rules}
        unknown = wanted - known
        if unknown:
            raise ValueError(
                f"unknown rule(s) {sorted(unknown)}; known: {sorted(known)}"
            )
        rules = [r for r in rules if r.name in wanted]
    return rules
