"""Thread-lifecycle pass (rule ``thread-join``, pass ``threads``).

Every ``threading.Thread(...)`` spawn site must have an OWNER that
joins it: the enclosing function, or (for spawns inside methods) some
method of the enclosing class, must contain a ``.join(...)`` call. A
spawned thread nobody joins outlives its work — interpreter teardown
kills it mid-call (the 'terminate called / FATAL: exception not
rethrown' crash utils/concurrent.iter_on_thread documents, and the
leaked-thread pattern tests/test_ingest.py guards dynamically with
before/after thread counts — this pass is the static version).

``daemon=True`` is NOT an escape: daemon threads still die mid-call at
teardown; it only changes whether the interpreter waits. Fire-and-
forget threads that are genuinely unjoinable declare it:

    # pslint: disable=thread-join — <who owns the lifetime and why>

Purely syntactic: the pass proves a join SITE exists in the owning
scope, not that every path reaches it — that's what the dynamic
leak-guard tests are for. The two checks are complementary.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence

from .engine import Finding, Rule, SourceFile, walk_package


def _is_thread_ctor(node: ast.Call) -> bool:
    fn = node.func
    if isinstance(fn, ast.Attribute) and fn.attr == "Thread":
        return isinstance(fn.value, ast.Name) and fn.value.id == "threading"
    return isinstance(fn, ast.Name) and fn.id == "Thread"


def _is_thread_join(call: ast.Call) -> bool:
    """A Thread.join-shaped call: ``t.join()``, ``t.join(5)``,
    ``t.join(timeout=...)``. ``str.join`` / ``os.path.join`` take a
    non-numeric positional argument, so they never match — a
    ``", ".join(parts)`` in the owning class must not satisfy the
    thread-lifecycle rule."""
    fn = call.func
    if not (isinstance(fn, ast.Attribute) and fn.attr == "join"):
        return False
    if isinstance(fn.value, ast.Constant):  # literal like ", ".join
        return False
    if any(kw.arg == "timeout" for kw in call.keywords):
        return True
    if not call.args:
        return not call.keywords
    return len(call.args) == 1 and (
        isinstance(call.args[0], ast.Constant)
        and isinstance(call.args[0].value, (int, float))
    )


def _contains_join(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Call) and _is_thread_join(n):
            return True
    return False


class ThreadLifecycleRule(Rule):
    name = "threads"
    version = "2"
    per_file = True  # no cross-file state: content-hash cacheable

    def __init__(self, scope: Optional[Sequence[str]] = None):
        self.scope = scope

    def paths(self, root: str) -> Sequence[str]:
        if self.scope is not None:
            return self.scope
        return walk_package(root)

    def check(self, files: Dict[str, SourceFile], root: str) -> List[Finding]:
        findings: List[Finding] = []
        for sf in files.values():
            findings.extend(self._check_file(sf))
        return findings

    def _check_file(self, sf: SourceFile) -> List[Finding]:
        findings: List[Finding] = []
        # parent chain: function defs and class defs enclosing each node
        # (built once per file by the engine, shared across passes)
        parents = sf.parents()

        def owners(node: ast.AST):
            cur = parents.get(node)
            while cur is not None:
                if isinstance(
                    cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
                ):
                    yield cur
                cur = parents.get(cur)

        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.Call) and _is_thread_ctor(node)):
                continue
            joined = False
            for owner in owners(node):
                if _contains_join(owner):
                    joined = True
                    break
                if isinstance(owner, ast.ClassDef):
                    break  # a class boundary is the widest owner scope
            if not joined:
                findings.append(
                    Finding(
                        sf.rel,
                        node.lineno,
                        "thread-join",
                        "threading.Thread spawned with no owner that "
                        "joins it (no .join() in the enclosing function "
                        "or class); join it, or disable with a reason",
                    )
                )
        return findings
