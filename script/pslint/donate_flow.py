"""Use-after-donate dataflow pass (rule ``use-after-donate``).

``donate_argnums`` hands a buffer's storage to XLA: after the call the
Python binding still *names* the donated array, but touching it raises
(or worse, silently reads freed storage on some backends). The runtime
already papers over double-donation with the retry-undonated fallback
(ops/kv_ops.py) — this pass makes the hazard a commit-time finding
instead of a runtime fallback counter.

What it tracks, per function body, in source order:

- a call whose callee **donates** positional arguments marks each
  donated argument expression's *binding path* (``buf``, ``c.table``,
  ``box[0]`` — attribute chains and subscripts, subscripts wildcarded)
  as dead from that line;
- a later **read** of a dead path (or any extension of it —
  ``c.table.shape`` after ``c.table`` was donated) is a finding; passing
  it to another call (re-submit), ``len()``, returning it all count,
  because they are all reads of the donated binding;
- **rebinding kills**: assigning to the path (or a prefix of it)
  revives the binding. Assignment VALUES are processed before their
  targets, so the canonical ``c.table = kv_ops.push_donated(c.table,
  ...)`` round-trip — donate then immediately rebind — is clean;
- **branches don't see each other**: each arm of an ``if``/``try``
  analyzes a copy of the state; a donation in one arm and a use in its
  sibling never pair up. Donations do flow *out* of branches
  (may-donate), and a kill in any arm clears (may-kill) — the pass
  prefers missing a path-sensitive bug to flagging correct code.

Which callees donate comes from the project symbol table
(``Project.donating()``): ``donate_argnums=`` declarations anywhere in
an assignment's value or a decorator, ``# donates: <pos>[,<pos>]``
annotations on a ``def`` line, one level of wrapper propagation (a
function that forwards its own parameter at a donated position of a
donating callee donates that parameter), and the naming heuristic — an
unresolvable callee whose terminal name ends in ``_donated`` donates
its first positional argument (the ``push_donated`` /
``kv_push_pull_donated`` wrapper shape).

Blind spots, by design: aliasing (``alias = buf`` before donation is
invisible), donation through container elements other than the exact
subscript path, and flows deeper than one wrapper level. Escape hatch
for deliberate post-donation touches (there should be almost none):
``# donated-dead: <reason>`` on the use line, or the standard
``# pslint: disable=use-after-donate — <reason>``.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Tuple

from .engine import (
    DONATED_NAME_RE,
    Finding,
    Rule,
    SourceFile,
    _donate_positions as _donate_pos,
    callee_chain,
    walk_package,
)

DONATED_DEAD_RE = re.compile(r"#\s*donated-dead:\s*\S")


def _name_sources(value: ast.AST) -> List[str]:
    """Plain names a value could be an alias of: ``a``, ``a if c else
    b``, ``a or b`` — the donating-selector idioms."""
    if isinstance(value, ast.Name):
        return [value.id]
    if isinstance(value, ast.IfExp):
        return _name_sources(value.body) + _name_sources(value.orelse)
    if isinstance(value, ast.BoolOp):
        out: List[str] = []
        for v in value.values:
            out.extend(_name_sources(v))
        return out
    return []

Path = Tuple[str, ...]


def _path(node: ast.AST) -> Optional[Path]:
    """Binding path of an expression: ``c.table`` -> ("c", "table"),
    ``box[0]`` -> ("box", "[]"); None when not a plain chain."""
    parts: List[str] = []
    while True:
        if isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        elif isinstance(node, ast.Subscript):
            parts.append("[]")
            node = node.value
        elif isinstance(node, ast.Name):
            parts.append(node.id)
            break
        else:
            return None
    return tuple(reversed(parts))


def _extends(path: Path, dead: Path) -> bool:
    """Does reading ``path`` touch the dead binding? True when ``path``
    equals or descends from ``dead``."""
    return len(path) >= len(dead) and path[: len(dead)] == dead


class _FunctionAnalysis:
    """Linear may-analysis over one function body."""

    def __init__(self, rule: "UseAfterDonateRule", sf: SourceFile, donating):
        self.rule = rule
        self.sf = sf
        self.donating = donating
        self.local: Dict[str, Tuple[int, ...]] = {}
        self.findings: List[Finding] = []

    def seed_locals(self, fn: ast.AST) -> None:
        """Function-local donating names (kept OUT of the project map so
        a local ``fn = jax.jit(..., donate_argnums=...)`` cannot poison
        unrelated files): direct assigns with ``donate_argnums``, nested
        defs with donating decorators, and aliases of donating names —
        including the ``fn = donating if flag else plain`` selector
        idiom, which unions the arms (may-donate)."""
        for _ in range(2):  # aliases of aliases settle on pass two
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign):
                    pos = set(_donate_pos(node.value))
                    for src in _name_sources(node.value):
                        pos.update(self.local.get(src, ()))
                        pos.update(self.donating.get(src, ()))
                    if pos:
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                self.local[t.id] = tuple(sorted(pos))
                elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    if node is fn:
                        continue
                    for dec in node.decorator_list:
                        dpos = _donate_pos(dec)
                        if dpos:
                            self.local[node.name] = dpos

    def call_donates(self, call: ast.Call) -> Tuple[int, ...]:
        name = callee_chain(call)[-1]
        if isinstance(call.func, ast.Name) and call.func.id in self.local:
            return self.local[call.func.id]
        if name in self.donating:
            return self.donating[name]
        if DONATED_NAME_RE.search(name):
            return (0,)
        return ()

    # -- statements ---------------------------------------------------

    def stmts(self, body, dead: Dict[Path, Tuple[int, str]]) -> bool:
        """Returns True when the body definitely terminates (return/
        raise/break/continue) — its state must not flow past a branch."""
        for stmt in body:
            if self.stmt(stmt, dead):
                return True
        return False

    def stmt(self, stmt, dead: Dict[Path, Tuple[int, str]]) -> bool:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return False  # nested defs analyze as their own functions
        if isinstance(stmt, ast.Assign):
            self.expr(stmt.value, dead)
            for t in stmt.targets:
                self.kill(t, dead)
            return False
        if isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self.expr(stmt.value, dead)
            self.kill(stmt.target, dead)
            return False
        if isinstance(stmt, ast.AugAssign):
            self.expr(stmt.value, dead)
            self.expr(stmt.target, dead)  # augmented assign READS too
            self.kill(stmt.target, dead)
            return False
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self.expr(stmt.iter, dead)
            self.kill(stmt.target, dead)
            self.branches([stmt.body, stmt.orelse], dead)
            return False
        if isinstance(stmt, ast.While):
            self.expr(stmt.test, dead)
            self.branches([stmt.body, stmt.orelse], dead)
            return False
        if isinstance(stmt, ast.If):
            self.expr(stmt.test, dead)
            return self.branches([stmt.body, stmt.orelse], dead)
        if isinstance(stmt, ast.Try):
            self.branches(
                [stmt.body + stmt.orelse]
                + [h.body for h in stmt.handlers],
                dead,
            )
            return self.stmts(stmt.finalbody, dead)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self.expr(item.context_expr, dead)
                if item.optional_vars is not None:
                    self.kill(item.optional_vars, dead)
            return self.stmts(stmt.body, dead)
        if isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self.expr(stmt.value, dead)
            return True
        if isinstance(stmt, ast.Raise):
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    self.expr(child, dead)
            return True
        if isinstance(stmt, (ast.Break, ast.Continue)):
            return True
        if isinstance(stmt, ast.Expr):
            self.expr(stmt.value, dead)
            return False
        if isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                self.kill(t, dead)  # del is an explicit drop, not a read
            return False
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self.expr(child, dead)
            elif isinstance(child, ast.stmt):
                self.stmt(child, dead)
        return False

    def branches(self, arms, dead: Dict[Path, Tuple[int, str]]) -> bool:
        """Each arm runs on a copy; afterwards donations union out
        (may-donate) and any arm's kill clears (may-kill). An arm that
        definitely terminates contributes nothing to fall-through state
        — `if cond: return donating(x)` leaves x alive after the if.
        Returns True when EVERY arm terminates."""
        base = dict(dead)
        states = []
        terminated_all = bool(arms)
        for arm in arms:
            s = dict(base)
            if self.stmts(arm, s):
                continue  # no fall-through from this arm
            terminated_all = False
            states.append(s)
        if not states:
            return terminated_all
        killed = set()
        for s in states:
            for p in base:
                if p not in s:
                    killed.add(p)
        dead.clear()
        for s in states:
            for p, v in s.items():
                if p not in killed:
                    dead.setdefault(p, v)
        return False

    # -- expressions --------------------------------------------------

    def expr(self, node: ast.AST, dead: Dict[Path, Tuple[int, str]]):
        if isinstance(node, ast.Lambda):
            return  # deferred body: runs later, order unknowable here
        if isinstance(node, ast.Call):
            # uses are checked against the PRE-call dead set: the arg
            # being donated by this very call is the donation itself,
            # not a use — but an already-dead arg is a re-submit
            for child in list(node.args) + [kw.value for kw in node.keywords]:
                self.expr(child, dead)
            self.expr(node.func, dead)
            positions = self.call_donates(node)
            for pos in positions:
                if pos < len(node.args):
                    p = _path(node.args[pos])
                    if p is not None:
                        dead[p] = (node.lineno, callee_chain(node)[-1])
            return
        if isinstance(node, (ast.Attribute, ast.Subscript, ast.Name)):
            p = _path(node)
            if p is not None:
                self.use(node, p, dead)
                return
            # unchained base (e.g. f().x): descend into the value
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self.expr(child, dead)
            elif isinstance(child, ast.comprehension):
                self.expr(child.iter, dead)
                for cond in child.ifs:
                    self.expr(cond, dead)

    def use(self, node: ast.AST, path: Path, dead):
        for dpath, (dline, callee) in dead.items():
            if not _extends(path, dpath):
                continue
            line = node.lineno
            if DONATED_DEAD_RE.search(self.sf.comment_at_or_above(line)):
                return
            self.findings.append(
                Finding(
                    self.sf.rel,
                    line,
                    "use-after-donate",
                    f"'{'.'.join(path)}' was donated to {callee}() on line "
                    f"{dline} and is dead; rebind it from the call's result "
                    "or mark the use '# donated-dead: <reason>'",
                )
            )
            return

    def kill(self, target: ast.AST, dead):
        if isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self.kill(el, dead)
            return
        if isinstance(target, ast.Starred):
            self.kill(target.value, dead)
            return
        p = _path(target)
        if p is None:
            return
        # assigning to a path revives it and everything beneath it
        for dpath in [d for d in dead if _extends(d, p)]:
            del dead[dpath]
        # subscript/attr writes into a dead buffer are themselves uses:
        # box[0][3] = v after box[0] was donated writes freed storage
        if len(p) > 1:
            prefix = p[:-1]
            for dpath in list(dead):
                if _extends(prefix, dpath):
                    self.use(target, prefix, dead)
                    return


class UseAfterDonateRule(Rule):
    name = "use-after-donate"
    version = "1"

    def __init__(self, scope: Optional[Sequence[str]] = None):
        self.scope = tuple(scope) if scope is not None else None

    def paths(self, root: str) -> Sequence[str]:
        if self.scope is not None:
            return self.scope
        return walk_package(root)

    def check(self, files: Dict[str, SourceFile], root: str) -> List[Finding]:
        project = self.get_project(files)
        donating = project.donating()
        findings: List[Finding] = []
        for sf in files.values():
            for node in ast.walk(sf.tree):
                if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                fa = _FunctionAnalysis(self, sf, donating)
                fa.seed_locals(node)
                fa.stmts(node.body, {})
                findings.extend(fa.findings)
        return findings
