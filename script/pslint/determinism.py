"""Determinism pass (rule ``determinism``).

The replay invariant (ROADMAP, PR 6/11): re-running the same workload
byte-stream must reproduce the same bytes — wire encodings, FTRL
updates, checkpoint contents are all bit-identity contracts. Modules
under that contract declare it with a ``# bit-identical`` marker
comment (conventionally in the module docstring's vicinity); this pass
sweeps each scoped module for sources of run-to-run nondeterminism:

- **set iteration feeding output** — ``for k in someset``, packing a
  set/set-comprehension into ``list()``/``tuple()``/``sorted`` absent,
  or a set/dict comprehension flowing into an ``np.array``-shaped
  packing call: Python set order varies with hash seeding;
- **unsorted directory walks** — ``os.listdir``, ``glob.glob`` /
  ``iglob``, ``scandir``, ``iterdir`` return OS order; wrap in
  ``sorted(...)``;
- **unseeded RNG** — module-global ``random.*`` draws,
  ``random.Random()`` / ``np.random.default_rng()`` with no seed, and
  the legacy ``np.random.*`` draw functions;
- **wall-clock reads** — ``time.time`` / ``time_ns``,
  ``datetime.now`` / ``utcnow`` / ``today``: anything derived from them
  differs per run. ``perf_counter`` / ``monotonic`` are allowed — they
  time telemetry, they must never feed output (that is a review
  contract this pass cannot check).

A scoped module MISSING the ``# bit-identical`` marker is itself a
finding — the scope list below and the in-file annotations stay in
lockstep, so moving a module out of the contract is an explicit edit
in both places.

Syntactic only: a set bound to a variable and iterated two lines later
is invisible, as is a wall-clock value laundered through a helper. The
pass catches the direct forms; the replay tests catch the rest.
Suppress deliberate uses (telemetry timestamps in a wire header, a
seeded-by-caller RNG) with
``# pslint: disable=determinism — <reason>``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Sequence

from .engine import (
    BIT_IDENTICAL_RE,
    Finding,
    Rule,
    SourceFile,
    callee_chain,
)

#: the bit-identity contract surface (doc/STATIC_ANALYSIS.md)
SCOPE = (
    "parameter_server_tpu/learner/wire.py",
    "parameter_server_tpu/learner/ingest.py",
    "parameter_server_tpu/ops/wire_codec.py",
    "parameter_server_tpu/ops/ftrl.py",
    "parameter_server_tpu/ops/ftrl_sparse.py",
    "parameter_server_tpu/ops/significance.py",
    "parameter_server_tpu/learner/consistency.py",
    "parameter_server_tpu/parameter/kv_vector.py",
    "parameter_server_tpu/parameter/replica.py",
)

_DIR_WALKS = {"listdir", "glob", "iglob", "scandir", "iterdir"}
_RANDOM_DRAWS = {
    "random", "randint", "randrange", "choice", "choices", "shuffle",
    "sample", "uniform", "gauss", "normalvariate", "betavariate",
}
_NP_DRAWS = {
    "rand", "randn", "randint", "random", "random_sample", "choice",
    "permutation", "shuffle", "uniform", "normal", "standard_normal",
}
_WALL_CLOCK = {
    ("time", "time"),
    ("time", "time_ns"),
    ("datetime", "now"),
    ("datetime", "utcnow"),
    ("date", "today"),
}
_NP_PACKERS = {"array", "asarray", "fromiter", "stack", "concatenate", "hstack", "vstack"}


def _is_set_expr(node: ast.AST) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        chain = callee_chain(node)
        if chain[-1] == "set":
            return True
        # set ops that yield sets: a.union(b) etc on literal sets
        if (
            chain[-1] in ("union", "intersection", "difference")
            and node.args
            and isinstance(node.func, ast.Attribute)
        ):
            return _is_set_expr(node.func.value)
    if isinstance(node, ast.BinOp) and isinstance(
        node.op, (ast.BitOr, ast.BitAnd, ast.Sub)
    ):
        return _is_set_expr(node.left) or _is_set_expr(node.right)
    return False


class DeterminismRule(Rule):
    name = "determinism"
    version = "1"
    per_file = True  # purely per-file: content-hash cacheable

    def __init__(self, scope: Sequence[str] = SCOPE):
        self.scope = tuple(scope)

    def paths(self, root: str) -> Sequence[str]:
        return self.scope

    def check(self, files: Dict[str, SourceFile], root: str) -> List[Finding]:
        findings: List[Finding] = []
        for sf in files.values():
            findings.extend(self._check_file(sf))
        return findings

    def _check_file(self, sf: SourceFile) -> List[Finding]:
        findings: List[Finding] = []
        marked = any(
            BIT_IDENTICAL_RE.search(c) for c in sf.comments.values()
        ) or BIT_IDENTICAL_RE.search(
            ast.get_docstring(sf.tree) or ""
        )
        if not marked:
            findings.append(
                Finding(
                    sf.rel,
                    1,
                    "determinism",
                    "module is in the bit-identity scope but carries no "
                    "'# bit-identical' marker comment — add the marker "
                    "(or move the module out of the determinism scope)",
                )
            )
        parents = sf.parents()

        def inside_sorted(node: ast.AST) -> bool:
            p = parents.get(node)
            hops = 0
            while p is not None and hops < 3:
                if isinstance(p, ast.Call) and callee_chain(p)[-1] in (
                    "sorted", "frozenset", "set", "len", "min", "max", "sum",
                ):
                    # sorted() restores order; the others are
                    # order-insensitive consumers
                    return True
                p = parents.get(p)
                hops += 1
            return False

        def flag(node, msg):
            findings.append(Finding(sf.rel, node.lineno, "determinism", msg))

        for node in ast.walk(sf.tree):
            # set iteration feeding anything ordered
            if isinstance(node, (ast.For, ast.AsyncFor)) and _is_set_expr(
                node.iter
            ):
                flag(node.iter, "iterating a set: order varies per run; "
                     "iterate sorted(...) instead")
            elif isinstance(node, ast.comprehension) and _is_set_expr(
                node.iter
            ):
                flag(node.iter, "comprehension over a set: order varies "
                     "per run; use sorted(...)")
            elif isinstance(node, ast.Call):
                chain = callee_chain(node)
                tail = chain[-1]
                # list(someset) / tuple(someset) packs set order
                if (
                    tail in ("list", "tuple")
                    and node.args
                    and _is_set_expr(node.args[0])
                ):
                    flag(node, f"{tail}() over a set packs hash order; "
                         "use sorted(...)")
                elif tail in _DIR_WALKS and not inside_sorted(node):
                    flag(node, f"{tail}() returns OS order; wrap in "
                         "sorted(...)")
                elif (
                    len(chain) >= 2
                    and chain[-2] == "random"
                    and chain[0] in ("np", "numpy")
                    and tail in _NP_DRAWS
                ):
                    flag(node, f"legacy np.random.{tail}() draws from the "
                         "process-global unseeded stream; thread a "
                         "seeded Generator through instead")
                elif (
                    len(chain) == 2
                    and chain[0] == "random"
                    and tail in _RANDOM_DRAWS
                ):
                    flag(node, f"random.{tail}() uses the unseeded global "
                         "RNG; use a seeded random.Random(seed)")
                elif (
                    tail in ("Random", "default_rng") and not node.args
                    and not node.keywords
                ):
                    flag(node, f"{tail}() with no seed is seeded from the "
                         "OS; pass an explicit seed")
                elif len(chain) >= 2 and chain[-2:] in _WALL_CLOCK:
                    flag(node, f"wall-clock read {'.'.join(chain)}() is "
                         "nondeterministic across runs; derive from the "
                         "replayed stream or suppress with a reason")
                elif tail in _NP_PACKERS and chain[0] in ("np", "numpy"):
                    for arg in node.args:
                        if _is_set_expr(arg) or isinstance(
                            arg, ast.DictComp
                        ):
                            flag(node, f"np.{tail}() packing a set/dict "
                                 "comprehension bakes hash/insertion "
                                 "order into an array; sort the keys "
                                 "first")
                            break
        return findings
