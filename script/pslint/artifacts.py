"""Cross-artifact consistency pass (rule ``cross-artifact``).

Names that cross an artifact boundary — a fault-point string in code, a
metric name in an alert JSON, a benchmark key in the Makefile — have no
compiler: when one side drifts, the other becomes a silent no-op (an
alert that never fires, a drill that never injects). This pass pins
each reference side to its truth side and fails the lint on drift.
Finding sub-rules (suppression keys):

- ``fault-point`` — the point name at every ``faults.inject`` /
  ``faults.arm`` / ``faults.scoped`` call site must be a member of
  ``faults.POINTS`` (the runtime rejects unknown names too, but only
  when that code path actually runs — a drill nobody exercises drifts
  silently);
- ``alert-metric`` — every ``"metric"`` / ``"den"`` name in
  ``configs/alerts/*.json`` must exist in the instruments catalog
  (``telemetry/instruments.py`` string constants): a rule over a
  renamed metric evaluates forever against an absent series;
- ``bench-wiring`` — every benchmark key the Makefile invokes
  (``python -m parameter_server_tpu.benchmarks <key>``) must exist in
  the ``@benchmark("<key>")`` REGISTRY; every REGISTRY key must be
  referenced somewhere (Makefile, ``script/onchip.py``, or
  ``tests/test_benchmarks.py``) so registered benchmarks cannot become
  unreachable dead code;
- ``metadata-section`` — every name in ``script/bench_diff.py``'s
  ``METADATA_SECTIONS`` must appear as a string constant in the bench
  record producers (``bench.py`` / ``benchmarks/components.py``): a
  section nobody writes is stale exclusion config.

Direction matters: each check points from the REFERENCE (call site,
config, Makefile) at its TRUTH (POINTS, catalog, REGISTRY). The
reverse direction — e.g. a POINTS entry no drill arms — is reported
only for REGISTRY keys, where an unreferenced entry is definitionally
dead; POINTS / catalog entries may be armed by tests or operators at
runtime.

Findings in non-Python artifacts (JSON, Makefile) cannot carry inline
suppressions; fix the drift or adjust the truth side instead.
"""

from __future__ import annotations

import ast
import glob
import json
import os
import re
from typing import Dict, List, Sequence, Set

from .engine import Finding, Rule, SourceFile, callee_chain, walk_package

_FAULT_FNS = {"inject", "arm", "scoped"}
_BENCH_INVOKE_RE = re.compile(
    r"-m\s+parameter_server_tpu\.benchmarks\s+([A-Za-z_][A-Za-z0-9_]*)"
)

_FAULTS_MOD = "parameter_server_tpu/system/faults.py"
_INSTRUMENTS_MOD = "parameter_server_tpu/telemetry/instruments.py"
_COMPONENTS_MOD = "parameter_server_tpu/benchmarks/components.py"
_BENCH_MOD = "bench.py"  # the record assembler lives at the repo root
_BENCH_DIFF = "script/bench_diff.py"


def _string_constants(tree: ast.AST) -> Set[str]:
    return {
        n.value
        for n in ast.walk(tree)
        if isinstance(n, ast.Constant) and isinstance(n.value, str)
    }


def _find_line(text: str, needle: str, start: int = 0) -> int:
    """1-based line of the first occurrence of ``needle`` at/after
    character ``start`` (1 if absent — a finding beats no finding)."""
    idx = text.find(needle, start)
    if idx < 0:
        return 1
    return text.count("\n", 0, idx) + 1


class CrossArtifactRule(Rule):
    name = "cross-artifact"
    version = "1"

    def paths(self, root: str) -> Sequence[str]:
        return tuple(walk_package(root)) + (_BENCH_MOD, _BENCH_DIFF)

    def check(self, files: Dict[str, SourceFile], root: str) -> List[Finding]:
        findings: List[Finding] = []
        findings.extend(self._check_fault_points(files))
        findings.extend(self._check_alert_metrics(files, root))
        findings.extend(self._check_bench_wiring(files, root))
        findings.extend(self._check_metadata_sections(files))
        return findings

    # -- fault points --------------------------------------------------

    def _points(self, files) -> Set[str]:
        sf = files.get(_FAULTS_MOD)
        if sf is None:
            return set()
        for node in sf.tree.body:
            if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "POINTS"
                for t in node.targets
            ):
                return {
                    el.value
                    for el in getattr(node.value, "elts", ())
                    if isinstance(el, ast.Constant)
                    and isinstance(el.value, str)
                }
        return set()

    def _check_fault_points(self, files) -> List[Finding]:
        points = self._points(files)
        if not points:
            return []  # fixture trees without faults.py: nothing to pin
        findings: List[Finding] = []
        for sf in files.values():
            if sf.rel == _FAULTS_MOD:
                continue  # the catalog's own docstring examples
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Call):
                    continue
                chain = callee_chain(node)
                # qualified calls only: blackbox.arm() is a different arm
                if len(chain) < 2 or chain[-2] != "faults":
                    continue
                if chain[-1] not in _FAULT_FNS or not node.args:
                    continue
                arg = node.args[0]
                if not (
                    isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)
                ):
                    continue
                if arg.value not in points:
                    findings.append(
                        Finding(
                            sf.rel,
                            node.lineno,
                            "fault-point",
                            f"faults.{chain[-1]}('{arg.value}') names a "
                            "point not in faults.POINTS — the injection "
                            "is a silent no-op; add the point or fix "
                            "the name",
                        )
                    )
        return findings

    # -- alert metrics -------------------------------------------------

    def _catalog(self, files) -> Set[str]:
        sf = files.get(_INSTRUMENTS_MOD)
        if sf is None:
            return set()
        return {
            s for s in _string_constants(sf.tree) if s.startswith("ps_")
        }

    def _check_alert_metrics(self, files, root: str) -> List[Finding]:
        catalog = self._catalog(files)
        if not catalog:
            return []
        findings: List[Finding] = []
        for path in sorted(
            glob.glob(os.path.join(root, "configs", "alerts", "*.json"))
        ):
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            try:
                with open(path, "r", encoding="utf-8") as f:
                    text = f.read()
                data = json.loads(text)
            except (OSError, ValueError) as e:
                findings.append(
                    Finding(rel, 1, "alert-metric", f"unreadable: {e}")
                )
                continue
            names: List[str] = []

            def collect(obj):
                if isinstance(obj, dict):
                    for key in ("metric", "den"):
                        v = obj.get(key)
                        if isinstance(v, str):
                            names.append(v)
                        elif isinstance(v, list):
                            names.extend(x for x in v if isinstance(x, str))
                    for v in obj.values():
                        collect(v)
                elif isinstance(obj, list):
                    for v in obj:
                        collect(v)

            collect(data)
            for name in names:
                if name not in catalog:
                    findings.append(
                        Finding(
                            rel,
                            _find_line(text, f'"{name}"'),
                            "alert-metric",
                            f"alert rule references metric '{name}' which "
                            "is not in the instruments catalog "
                            f"({_INSTRUMENTS_MOD}) — the rule will never "
                            "see a sample",
                        )
                    )
        return findings

    # -- benchmark wiring ----------------------------------------------

    def _registry(self, files) -> Dict[str, int]:
        """@benchmark("key") -> decorator line."""
        sf = files.get(_COMPONENTS_MOD)
        out: Dict[str, int] = {}
        if sf is None:
            return out
        for node in ast.walk(sf.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for dec in node.decorator_list:
                if (
                    isinstance(dec, ast.Call)
                    and callee_chain(dec)[-1] == "benchmark"
                    and dec.args
                    and isinstance(dec.args[0], ast.Constant)
                    and isinstance(dec.args[0].value, str)
                ):
                    out[dec.args[0].value] = dec.lineno
        return out

    def _check_bench_wiring(self, files, root: str) -> List[Finding]:
        registry = self._registry(files)
        if not registry:
            return []
        findings: List[Finding] = []
        mk_path = os.path.join(root, "Makefile")
        try:
            with open(mk_path, "r", encoding="utf-8") as f:
                mk_text = f.read()
        except OSError:
            mk_text = ""
        for i, line in enumerate(mk_text.splitlines(), start=1):
            for m in _BENCH_INVOKE_RE.finditer(line):
                key = m.group(1)
                if key not in registry:
                    findings.append(
                        Finding(
                            "Makefile",
                            i,
                            "bench-wiring",
                            f"Makefile invokes benchmark '{key}' which is "
                            "not a registered @benchmark key in "
                            f"{_COMPONENTS_MOD}",
                        )
                    )
        # reverse direction: a REGISTRY key nothing references is dead
        ref_texts = [mk_text]
        for rel in ("script/onchip.py", "tests/test_benchmarks.py"):
            try:
                with open(
                    os.path.join(root, rel), "r", encoding="utf-8"
                ) as f:
                    ref_texts.append(f.read())
            except OSError:
                pass
        for key, line in sorted(registry.items()):
            if not any(f'"{key}"' in t or f"'{key}'" in t or
                       re.search(rf"\b{re.escape(key)}\b", t)
                       for t in ref_texts):
                findings.append(
                    Finding(
                        _COMPONENTS_MOD,
                        line,
                        "bench-wiring",
                        f"benchmark '{key}' is registered but referenced "
                        "by no Makefile target, script/onchip.py, or "
                        "tests/test_benchmarks.py — unreachable "
                        "registration",
                    )
                )
        return findings

    # -- metadata sections ---------------------------------------------

    def _check_metadata_sections(self, files) -> List[Finding]:
        diff_sf = files.get(_BENCH_DIFF)
        if diff_sf is None:
            return []
        sections: Dict[str, int] = {}
        for node in ast.walk(diff_sf.tree):
            if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "METADATA_SECTIONS"
                for t in node.targets
            ):
                for c in ast.walk(node.value):
                    if isinstance(c, ast.Constant) and isinstance(
                        c.value, str
                    ):
                        sections[c.value] = c.lineno
        if not sections:
            return []
        producers: Set[str] = set()
        for rel in (_BENCH_MOD, _COMPONENTS_MOD):
            sf = files.get(rel)
            if sf is not None:
                producers |= _string_constants(sf.tree)
        if not producers:
            return []
        findings: List[Finding] = []
        for name, line in sorted(sections.items()):
            if name not in producers:
                findings.append(
                    Finding(
                        _BENCH_DIFF,
                        line,
                        "metadata-section",
                        f"METADATA_SECTIONS entry '{name}' is written by "
                        f"no bench record producer ({_BENCH_MOD} / "
                        f"{_COMPONENTS_MOD}) — stale exclusion config",
                    )
                )
        return findings
