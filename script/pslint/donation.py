"""Donation pass (rule ``donation``): script/donation_lint.py refitted
as an engine pass.

The logic stays in ``script/donation_lint.py`` (single source of truth
— tests/test_donation.py and the standalone ``make donation-lint``
alias keep importing it directly); this pass loads it by file path and
converts its ``rel:line: message`` problem strings into engine
findings, so ``make pslint`` runs the whole suite in one report and
pslint suppressions layer on top of the lint's own ``# no-donate:``
mechanism.
"""

from __future__ import annotations

import importlib.util
import os
import re
from typing import Dict, List, Sequence

from .engine import Finding, Rule, SourceFile

_PROBLEM_RE = re.compile(r"^(?P<path>[^:]+):(?P<line>\d+):\s*(?P<msg>.*)$")


def _load_sibling(name: str):
    """Import a script/<name>.py module by path (script/ is not a
    package; pslint lives one directory below it)."""
    path = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), f"{name}.py")
    spec = importlib.util.spec_from_file_location(f"_pslint_{name}", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class DonationRule(Rule):
    name = "donation"

    def paths(self, root: str) -> Sequence[str]:
        # parse the data-plane scope through the engine so pslint
        # suppressions and suppression-hygiene checks apply to it
        return tuple(_load_sibling("donation_lint").SCOPE)

    def check(self, files: Dict[str, SourceFile], root: str) -> List[Finding]:
        lint = _load_sibling("donation_lint")
        findings: List[Finding] = []
        for problem in lint.lint(root):
            m = _PROBLEM_RE.match(problem)
            if m is not None:
                findings.append(
                    Finding(
                        m.group("path").replace(os.sep, "/"),
                        int(m.group("line")),
                        self.name,
                        m.group("msg"),
                    )
                )
            else:  # e.g. "path: scoped module is missing"
                path = problem.split(":", 1)[0]
                msg = problem.split(":", 1)[-1].strip()
                findings.append(Finding(path, 1, self.name, msg))
        return findings
