"""jit-purity pass (rule ``jit-purity``).

A jitted function's Python body runs ONCE, at trace time; everything
that is not a traced jax op is baked or discarded. A telemetry call, a
``print``, a wall-clock read or a host-numpy materialization inside a
jitted data-plane function therefore *looks* like it works (it fires
during the first call) and then silently stops — or worse, forces a
device→host sync inside the hot path. This pass flags those constructs
inside functions that are direct jit targets in ``ops/``:

- ``print(...)`` — trace-time-only output;
- ``time.time()`` / ``time.perf_counter()`` / ``time.monotonic()`` —
  measures tracing, not execution (telemetry belongs OUTSIDE the jit,
  as ops/kv_ops._dispatch_fused does);
- telemetry instrument calls — ``.observe(...)`` / ``.inc(...)`` or any
  call into a ``*_tel`` / ``telemetry`` name;
- host numpy on traced values — ``np.asarray`` / ``np.array`` /
  ``np.copy`` / ``np.frombuffer`` / ``np.ascontiguousarray`` /
  ``np.save`` / ``np.random.*`` (``np.uint32(...)`` constants and
  shape math like ``np.sqrt(q.shape[-1])`` are trace-time constants
  and stay legal);
- ``.item()`` / ``.tolist()`` — forced device→host sync;
- ``nonlocal`` / ``global`` — closure mutation that happens once at
  trace time and never again.

A jit *target* is a function that is decorated with ``@jax.jit`` /
``@jit`` / ``@(functools.)partial(jax.jit, ...)``, or referenced by
name in a ``jit(f)`` / ``partial(jax.jit, ...)(f)`` call. Nested defs
inside a target are traced with it and are scanned too.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set

from .engine import (
    Finding,
    Rule,
    SourceFile,
    _is_jit_partial,
    _is_jit_ref,
    jit_target_names,
)

SCOPE = (
    "parameter_server_tpu/ops/kv_ops.py",
    "parameter_server_tpu/ops/ftrl.py",
    "parameter_server_tpu/ops/ftrl_sparse.py",
    "parameter_server_tpu/ops/quantize.py",
    "parameter_server_tpu/ops/flash_attention.py",
    "parameter_server_tpu/ops/wire_codec.py",
    # the KKT significance mask is trace-pure by contract (it runs
    # inside the sparse mini-step) — in scope like the rest of ops/
    "parameter_server_tpu/ops/significance.py",
    # the consistency runtime is host-side by design (collect/prep
    # thread hooks) — in scope for the same reason learning.py is
    "parameter_server_tpu/learner/consistency.py",
    # the learning plane is host-side by design — in scope so a future
    # jit sneaking telemetry calls inside a traced body is caught here
    # like it would be in ops/
    "parameter_server_tpu/telemetry/learning.py",
    # the declarative partitioner: spec resolution and rebalance
    # planning are host-side; only init_sharded jits (an init_fn it
    # does not author) — keep it honest under the same purity rules
    "parameter_server_tpu/parallel/partition.py",
)

_NP_IMPURE = {
    "asarray", "array", "copy", "frombuffer", "ascontiguousarray",
    "save", "savez", "load",
}
_TIME_FNS = {"time", "perf_counter", "monotonic", "process_time"}
_SYNC_METHODS = {"item", "tolist"}
_TEL_METHODS = {"observe", "inc"}


# jit-target discovery now lives in the engine's symbol table; kept
# under the old name for existing callers
_jit_target_names = jit_target_names


def _is_jitted_def(fn: ast.AST, by_name: Set[str]) -> bool:
    if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return False
    if fn.name in by_name:
        return True
    for dec in fn.decorator_list:
        if _is_jit_ref(dec) or _is_jit_partial(dec):
            return True
        if isinstance(dec, ast.Call) and _is_jit_ref(dec.func):
            return True
    return False


class JitPurityRule(Rule):
    name = "jit-purity"
    version = "2"
    per_file = True  # no cross-file state: content-hash cacheable

    def __init__(self, scope: Sequence[str] = SCOPE):
        self.scope = tuple(scope)

    def paths(self, root: str) -> Sequence[str]:
        return self.scope

    def check(self, files: Dict[str, SourceFile], root: str) -> List[Finding]:
        findings: List[Finding] = []
        project = self.get_project(files)
        for sf in files.values():
            by_name = project.jit_targets(sf.rel)
            for node in ast.walk(sf.tree):
                if _is_jitted_def(node, by_name):
                    findings.extend(self._check_body(node, sf))
        return findings

    def _check_body(self, fn, sf: SourceFile) -> List[Finding]:
        findings: List[Finding] = []

        def flag(node: ast.AST, what: str):
            findings.append(
                Finding(
                    sf.rel,
                    node.lineno,
                    "jit-purity",
                    f"{what} inside jitted function '{fn.name}' runs at "
                    "trace time only — move it outside the jit or "
                    "disable with a reason",
                )
            )

        for node in ast.walk(fn):
            if isinstance(node, (ast.Nonlocal, ast.Global)):
                flag(node, f"{type(node).__name__.lower()} mutation")
                continue
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Name) and f.id == "print":
                flag(node, "print()")
            elif isinstance(f, ast.Attribute):
                owner = f.value
                owner_name = owner.id if isinstance(owner, ast.Name) else None
                if owner_name == "time" and f.attr in _TIME_FNS:
                    flag(node, f"time.{f.attr}() clock read")
                elif owner_name == "np" and f.attr in _NP_IMPURE:
                    flag(node, f"host numpy np.{f.attr}()")
                elif (
                    isinstance(owner, ast.Attribute)
                    and owner.attr == "random"
                    and isinstance(owner.value, ast.Name)
                    and owner.value.id == "np"
                ):
                    flag(node, f"host numpy np.random.{f.attr}()")
                elif f.attr in _SYNC_METHODS and not node.args:
                    flag(node, f".{f.attr}() device→host sync")
                elif f.attr in _TEL_METHODS and self._telemetry_owner(owner):
                    flag(node, f"telemetry .{f.attr}() call")
        return findings

    @staticmethod
    def _telemetry_owner(owner: ast.AST) -> bool:
        """Owner expression smells like a telemetry instrument: a name
        (or subscript of a name) matching ``*tel*`` / ``*metric*`` /
        ``*instrument*``."""
        base: Optional[str] = None
        node = owner
        while isinstance(node, ast.Subscript):
            node = node.value
        if isinstance(node, ast.Name):
            base = node.id
        elif isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
            base = node.func.id
        if base is None:
            return False
        low = base.lower()
        return "tel" in low or "metric" in low or "instrument" in low
