#!/usr/bin/env bash
# Kill stray local runs (ref script/kill_node.sh).
pkill -f "parameter_server_tpu.apps" 2>/dev/null || true
