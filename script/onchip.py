#!/usr/bin/env python
"""On-chip evidence suite + tunnel watcher.

Round-2 verdict: the repo had code parity but ZERO valid hardware
artifacts (the axon tunnel was wedged the whole round). This script is
the fix: a persistent watcher (``--watch``) probes device init on a
schedule; the moment the probe succeeds it runs every pending evidence
task — each in its own subprocess with a timeout so a mid-task wedge
cannot hang the watcher — and appends every result as a timestamped
JSON line to ``BENCH_ONCHIP.md``.

Tasks (priority order — open round evidence first):
  link        host<->device bandwidth + device identity + HBM stats
  bench       python bench.py               (synthetic headline)
  lm          byte-LM train-step tokens/s + MFU, attention-mode
              comparison, and the >=100M-param MFU-push configs
  scale       largest FTRL table on one chip (2^28-2^31) with HBM
              accounting, f32 vs bf16 FTRL state
  serve       KV-cached decode (MHA/GQA/int8), beam search,
              speculative-decoding speedup with a trained draft
  bench_real  python bench.py --real --profile  (parse-in-loop +
              parity + named-scope device-time breakdown)
  flash       Pallas flash-attention kernels under REAL Mosaic:
              compile, fwd/bwd parity vs the XLA path, then GFLOP/s
  components  python -m parameter_server_tpu.benchmarks

State lives in doc/onchip_state.json (per-task status + attempts); the
watcher retries failed tasks up to --max-attempts, then keeps re-running
`link` + `bench` periodically to catch better tunnel-bandwidth windows.

Reference bar: the reference MEASURED its claims with dedicated perf
binaries (src/test/kv_vector_perf_ps.cc, network_perf_ps.cc); this file
is our equivalent discipline for the single tunneled chip.
"""

from __future__ import annotations

import argparse
import contextlib
import json
import math
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # children run as `python script/onchip.py`
    sys.path.insert(0, REPO)
# ONCHIP_SMOKE=1 shrinks every task to CPU-feasible shapes (and lets the
# flash task run in interpret mode) so the task CODE PATHS are testable
# without the chip; evidence runs never set it
SMOKE = bool(os.environ.get("ONCHIP_SMOKE"))
LOG_MD = os.path.join(REPO, "BENCH_ONCHIP.md")
STATE = os.path.join(REPO, "doc", "onchip_state.json")
WATCH_LOG = os.path.join(REPO, "doc", "onchip_watch.log")

# (name, argv-or-None(=internal), timeout_s) — PRIORITY order: a short
# tunnel window should capture the round's open evidence first — the
# headline bench (the driver artifact's metric), then the LM MFU/decode
# /speculative captures and the big-table scale runs — before the
# already-well-evidenced flash kernels and component microbenches
TASKS = [
    ("link", None, 600),
    # timeouts sized for a CRAWLING-but-alive tunnel (11 MB/s windows
    # observed; bench now carries 600s compile graces): a legitimately
    # slow success must not be killed by its own timeout
    ("bench", [sys.executable, "bench.py"], 3600),
    # the reference's production pull config (1-byte fixing_float,
    # example/linear/ctr/online_l1lr.conf), captured under its own
    # _q1 metric so headline medians stay exact-pull. The narrow
    # codes+mask gather it was built to test measured SLOWER than
    # wide on TPU (08-02 A/B), so auto now realizes this config as
    # quantize → dequantize shard-wide → wide f32 gather
    ("bench_q1", [sys.executable, "bench.py", "--pull-bytes", "1"], 3600),
    ("lm", None, 5400),
    ("scale", None, 2400),
    ("serve", None, 5400),
    # speculative decoding at bandwidth-bound target scale (~1B
    # params): its own process because training peaks ~9 GB HBM
    ("spec_big", None, 2400),
    # --profile: one jax.profiler device trace of the first serialized
    # launch, summarized into the record by named-scope phase
    # (ps_pull/ps_compute/ps_push/ps_update) — the r3 verdict's
    # "where does the --real step time go" breakdown
    ("bench_real",
     [sys.executable, "bench.py", "--real",
      "--profile", "/tmp/ps_profile_real"], 5400),
    ("flash", None, 2400),
    ("components", [sys.executable, "-m", "parameter_server_tpu.benchmarks"], 3600),
    # last: optimization experiments, valuable but not round evidence
    ("gatherx", None, 1800),
]

# bf16 peak matmul FLOP/s by device_kind (public spec sheets); MFU is
# omitted for kinds not listed rather than guessed
PEAK_BF16 = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}

# physical HBM bandwidth by device_kind (public spec sheets), the
# sanity ceiling for any derived GB/s: a derived rate above this means
# the BYTES are overcounted or the TIMING under-measured, and the
# record must say so instead of publishing an impossible number
# (round-3 verdict: decode claimed 1387 GB/s on a ~819 GB/s part)
PEAK_HBM_GB_S = {
    "TPU v4": 1228.0,
    "TPU v5 lite": 819.0,
    "TPU v5e": 819.0,
    "TPU v5": 2765.0,
    "TPU v5p": 2765.0,
    "TPU v6 lite": 1640.0,
    "TPU v6e": 1640.0,
}


def _now() -> str:
    return time.strftime("%Y-%m-%d %H:%M:%S", time.localtime())


def emit(obj) -> None:
    """Task-side: one JSON line on stdout (parent appends to the log)."""
    print(json.dumps(obj), flush=True)


def _flush(x) -> None:
    """True device->host dependency (block_until_ready under-waits on the
    tunneled backend — bench.py measurement note)."""
    import jax
    import numpy as np

    np.asarray(jax.tree.leaves(x)[0].ravel()[:1])


def session_stats(metric: str, value: float, match: "dict | None" = None) -> dict:
    """Cross-session stability fields for a just-measured ``value``:
    median and relative spread over THIS capture plus every prior
    capture of the same metric in BENCH_ONCHIP.md. Single-shot on-chip
    numbers through the tunnel vary run-to-run by up to ~35% (r3
    verdict weak #8) — any line quoted as a headline should be the
    cross-session median, which these fields make self-contained.

    ``match``: key/value pairs a prior record must AGREE on to count
    (device_kind, shapes) — a CPU smoke capture or a re-shaped config
    must never pollute the on-chip median."""
    vals = [float(value)]
    for _ts, d in _iter_log_records(LOG_MD):
        if d.get("metric") != metric or not isinstance(
            d.get("value"), (int, float)
        ) or d["value"] <= 0:
            continue
        if d.get("exceeds_physical_peak") is True:
            # a record that flags its own bandwidth accounting as
            # physically impossible must not enter published medians
            continue
        if any(
            isinstance(v, float) and not math.isfinite(v)
            for v in d.values()
        ):
            # same rule as _chip_success: a degenerate capture (e.g.
            # NaN target_loss = diverged model) must not pool into
            # published medians
            continue
        if match and any(
            d.get(k) != v for k, v in match.items()
        ):
            continue  # missing key = no agreement (no pooling)
        vals.append(float(d["value"]))
    vals.sort()
    med = vals[len(vals) // 2]
    return {
        "sessions": len(vals),
        "median_across_sessions": round(med, 1),
        "session_spread": round((vals[-1] - vals[0]) / med, 3) if med else 0.0,
    }


def _median_windows(fn, flush, windows: int = 3, n: int = 10):
    """(median_sec_per_call, rel_spread): ``windows`` timing windows of
    ``n`` flushed calls each — the in-run half of the stability story
    (a single window is one GC pause away from a 1.5x error)."""
    secs = []
    for _ in range(windows):
        t0 = time.perf_counter()
        r = None
        for _ in range(n):
            r = fn()
        flush(r)
        secs.append((time.perf_counter() - t0) / n)
    secs.sort()
    med = secs[len(secs) // 2]
    return med, round((secs[-1] - secs[0]) / med, 3) if med else 0.0


# ---------------------------------------------------------------------------
# internal tasks (run inside a child process that owns the TPU client)
# ---------------------------------------------------------------------------


def task_link() -> int:
    import jax
    import numpy as np

    dev = jax.devices()[0]
    mb = 4 if SMOKE else 64
    host = np.random.default_rng(0).random(mb << 18, np.float32)  # mb MB
    # warm the transfer path once
    _flush(jax.device_put(host[: 1 << 18]))
    up = []
    down = []
    for _ in range(3):
        t0 = time.perf_counter()
        d = jax.device_put(host)
        _flush(d)
        up.append(host.nbytes / (time.perf_counter() - t0) / 1e6)
        t0 = time.perf_counter()
        np.asarray(d)
        down.append(host.nbytes / (time.perf_counter() - t0) / 1e6)
    stats = dev.memory_stats() or {}
    emit(
        {
            "metric": "link_bandwidth",
            "unit": "MB/s",
            "value": round(float(np.median(up)), 1),
            "host_to_device_mb_s": [round(x, 1) for x in up],
            "device_to_host_mb_s": [round(x, 1) for x in down],
            "device_kind": dev.device_kind,
            "platform": dev.platform,
            "hbm_bytes_in_use": stats.get("bytes_in_use"),
            "hbm_bytes_limit": stats.get("bytes_limit"),
        }
    )
    return 0


def task_flash() -> int:
    """The round-2 flagship that never touched hardware: compile the
    Pallas flash kernels under real Mosaic and prove fwd+bwd parity vs
    the XLA path, then time them."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from parameter_server_tpu.ops.flash_attention import (
        flash_attention,
        flash_mha,
    )

    interp = False
    if jax.devices()[0].platform != "tpu":
        if not SMOKE:
            emit({"metric": "flash_onchip", "error": "not on tpu"})
            return 1
        interp = True  # smoke: exercise the task code path via interpreter

    rng = np.random.default_rng(0)

    def rand(*shape):
        return jnp.asarray(rng.normal(size=shape).astype(np.float32) * 0.3)

    failures = []
    checks = []

    def check(name, got, want, tol):
        err = float(jnp.max(jnp.abs(got - want)))
        ok = bool(err <= tol)
        checks.append({"case": name, "max_abs_err": round(err, 7), "ok": ok})
        if not ok:
            failures.append(name)

    bh, s, d = (4, 256, 64) if SMOKE else (4, 1024, 64)
    q, k, v = rand(bh, s, d), rand(bh, s, d), rand(bh, s, d)

    # Forward-output tolerance. In interpret mode both paths are exact
    # f32 and agree to ~1e-5. On the real chip the MXU truncates matmul
    # inputs to bf16 under default precision, and the two paths
    # accumulate P·V in different orders (flash: chunked online-softmax
    # rescaling; XLA: one matmul over the full row), so the honest
    # numerical floor is bf16-truncation scale: ~1e-3 relative, observed
    # 1.4e-4..2.6e-4 absolute at these magnitudes. The softmax stats
    # (lse, ~8e-6) and every gradient (≤5e-5) pin the math itself.
    ftol = 2e-5 if interp else 5e-4

    def run(use_pallas, **kw):
        return flash_attention(
            q, k, v, use_pallas=use_pallas,
            interpret=interp if use_pallas else None, **kw,
        )

    t0 = time.perf_counter()
    # fwd parity across every masking variant the models use
    for name, kw in [
        ("fwd_full", dict(causal=False)),
        ("fwd_causal", dict(causal=True)),
        ("fwd_causal_offsets",
         dict(causal=True, q_offset=s // 2, k_offset=s // 4)),
        ("fwd_window", dict(causal=True, window=max(64, s // 4))),
        ("fwd_window64", dict(causal=True, window=64)),
    ]:
        o_p, l_p = run(True, with_lse=True, **kw)
        o_x, l_x = run(False, with_lse=True, **kw)
        check(name, o_p, o_x, ftol)
        check(name + "_lse", jnp.where(jnp.isneginf(l_x), 0, l_p),
              jnp.where(jnp.isneginf(l_x), 0, l_x), 2e-4)
    compile_fwd_s = time.perf_counter() - t0

    # bwd parity (both Pallas bwd kernels) on the variants with distinct
    # masking code paths
    t0 = time.perf_counter()
    for name, kw in [
        ("bwd_full", dict(causal=False)),
        ("bwd_causal", dict(causal=True)),
        ("bwd_window", dict(causal=True, window=max(64, s // 4))),
    ]:
        def loss(up):
            def f(q, k, v):
                out = flash_attention(
                    q, k, v, use_pallas=up,
                    interpret=interp if up else None, **kw
                )
                return jnp.sum(out * out)
            return f

        g_p = jax.grad(loss(True), argnums=(0, 1, 2))(q, k, v)
        g_x = jax.grad(loss(False), argnums=(0, 1, 2))(q, k, v)
        for arr_p, arr_x, which in zip(g_p, g_x, "qkv"):
            check(f"{name}_d{which}", arr_p, arr_x, 5e-5)
    compile_bwd_s = time.perf_counter() - t0

    # GQA through the mha wrapper
    b, sq, h, nh = 2, 512, 256, 8
    xq, xk, xv = rand(b, sq, h), rand(b, sq, h // 4), rand(b, sq, h // 4)
    o_p = flash_mha(xq, xk, xv, nh, causal=True, n_kv_heads=2,
                    use_pallas=True, interpret=interp)
    o_x = flash_mha(xq, xk, xv, nh, causal=True, n_kv_heads=2,
                    use_pallas=False)
    check("gqa_mha", o_p, o_x, ftol)

    emit(
        {
            "metric": "flash_onchip_parity",
            "value": len(failures),
            "unit": "failed_cases",
            "cases_run": len(checks),
            "failures": failures,
            "compile_fwd_s": round(compile_fwd_s, 1),
            "compile_bwd_s": round(compile_bwd_s, 1),
            "checks": checks,
        }
    )

    if SMOKE:
        return 1 if failures else 0

    # perf: fwd and train (fwd+bwd) GFLOP/s, flash vs the jitted XLA path
    dev_kind = jax.devices()[0].device_kind
    peak = PEAK_BF16.get(dev_kind)

    def bench_pair(rec, qq, kk, vv, fwd_flops):
        """Time fwd and train (fwd+bwd, 3.5x factor: bwd ~2.5x — dq +
        dkv recompute) for both paths into ``rec``. n=10 per window:
        lower rep counts under-amortize the ~30-90ms dispatch round
        trip (the 04:27 sweep-deflation finding); median of 3 windows
        + spread fields answer the run-to-run variance finding."""
        spreads = {}
        for label, up in (("xla", False), ("flash", True)):
            fn = jax.jit(
                lambda q, k, v, up=up: flash_attention(
                    q, k, v, causal=True, use_pallas=up,
                    interpret=False if up else None,
                )
            )
            _flush(fn(qq, kk, vv))  # compile
            sec, spreads[f"{label}_fwd"] = _median_windows(
                lambda: fn(qq, kk, vv), _flush
            )
            rec[f"{label}_fwd_gflops"] = round(fwd_flops / sec / 1e9, 1)

            gfn = jax.jit(
                jax.grad(
                    lambda q, k, v, up=up: jnp.sum(
                        flash_attention(
                            q, k, v, causal=True, use_pallas=up,
                            interpret=False if up else None,
                        )
                        ** 2
                    ),
                    argnums=(0, 1, 2),
                )
            )
            _flush(gfn(qq, kk, vv))
            sec, spreads[f"{label}_train"] = _median_windows(
                lambda: gfn(qq, kk, vv), _flush
            )
            rec[f"{label}_train_gflops"] = round(
                3.5 * fwd_flops / sec / 1e9, 1
            )
        if peak:
            rec["flash_fwd_mfu_vs_bf16_peak"] = round(
                rec["flash_fwd_gflops"] * 1e9 / peak, 4
            )
        rec["timing_windows"] = 3
        rec["window_spread"] = spreads
        rec["value"] = rec["flash_fwd_gflops"]
        rec.update(session_stats(
            rec["metric"], rec["value"],
            {"device_kind": rec["device_kind"], "bh": rec["bh"], "d": rec["d"]},
        ))
        emit(rec)
        return rec

    for s_len, dtype in ((4096, jnp.float32), (8192, jnp.float32),
                         (8192, jnp.bfloat16)):
        bh2 = 8
        qq, kk, vv = (rand(bh2, s_len, d).astype(dtype) for _ in range(3))
        fwd_flops = 4.0 * bh2 * s_len * s_len * d / 2  # causal half
        tag = "" if dtype == jnp.float32 else "_bf16"
        rec = bench_pair(
            {"metric": f"flash_perf_s{s_len}{tag}", "unit": "GFLOP/s",
             "bh": bh2, "d": d, "causal": True, "device_kind": dev_kind},
            qq, kk, vv, fwd_flops,
        )

    # the block sweep below seeds its default point from the s=8192
    # bf16 d=64 record; name it now rather than relying on `rec` still
    # holding that record after the intervening sweep loops
    seed_train_gflops = rec["flash_train_gflops"]

    # d_head sweep (bf16, s=8192, constant total work bh*d): q·kᵀ
    # reduces over d, so d=64 only half-fills the MXU's 128-deep
    # reduction — deeper heads should lift kernel efficiency at the
    # same FLOP count (the LM task's ring_flash_h4 mode is the
    # end-to-end consumer of this answer). Per-config guard: d>=128
    # with 512x512 blocks is an unmeasured VMEM regime, and a failure
    # here must not cost the block-sweep record below.
    for bh3, d3 in ((4, 128), (2, 256)):
        try:
            qq, kk, vv = (
                rand(bh3, 8192, d3).astype(jnp.bfloat16) for _ in range(3)
            )
            bench_pair(
                {"metric": f"flash_perf_s8192_bf16_d{d3}",
                 "unit": "GFLOP/s", "bh": bh3, "d": d3, "causal": True,
                 "device_kind": dev_kind},
                qq, kk, vv, 4.0 * bh3 * 8192 * 8192 * d3 / 2,
            )
        except Exception as e:
            emit({"metric": f"flash_perf_s8192_bf16_d{d3}",
                  "error": repr(e)[:300]})

    # bwd block-size sweep (bf16, s=8192): grid-step count and MXU
    # occupancy both move with block shape, so measure the candidates
    # instead of guessing. The first capture (04:14) found 512x512 at
    # 12998 GFLOP/s vs 8528 for the then-default 128x128 — which is why
    # the kernel default is now 512x512.
    s_len = 8192
    qq, kk, vv = (rand(bh2, s_len, d).astype(jnp.bfloat16) for _ in range(3))
    fwd_flops = 4.0 * bh2 * s_len * s_len * d / 2
    # seed the CURRENT default blocking from the perf loop above (same
    # shape, dtype, and 3.5x factor) instead of paying its ~24s bwd
    # compile a second time; key derived from the live signature so a
    # future default flip cannot mislabel the seeded point
    kwd = flash_attention.__kwdefaults__
    dkey = f"{kwd['block_q']}x{kwd['block_k']} (seeded default)"
    swept = {dkey: seed_train_gflops}
    for bq, bk in ((128, 128), (256, 128), (128, 256), (256, 256),
                   (512, 128), (128, 512), (512, 512)):
        if f"{bq}x{bk}" in dkey:
            continue  # already seeded from the default-blocking run
        key = f"{bq}x{bk}"
        try:
            gfn = jax.jit(
                jax.grad(
                    lambda q, k, v, bq=bq, bk=bk: jnp.sum(
                        flash_attention(
                            q, k, v, causal=True, use_pallas=True,
                            interpret=False, block_q=bq, block_k=bk,
                        )
                        ** 2
                    ),
                    argnums=(0, 1, 2),
                )
            )
            _flush(gfn(qq, kk, vv))
            # same timing discipline as bench_pair (median of 3 windows
            # of n=10): the seeded default point is median-protected,
            # so single-window candidates would lose outlier races to
            # it even when genuinely faster
            sec, _sp = _median_windows(lambda g=gfn: g(qq, kk, vv), _flush)
            swept[key] = round(3.5 * fwd_flops / sec / 1e9, 1)
        except Exception as e:  # e.g. VMEM overflow at 512x512
            swept[key] = f"error: {repr(e)[:120]}"
    numeric = {k: v for k, v in swept.items() if isinstance(v, float)}
    if numeric:
        best_key = max(numeric, key=numeric.get)
        rec = {
            "metric": "flash_train_blocksweep_s8192_bf16",
            "unit": "GFLOP/s",
            "value": numeric[best_key],
            "best_blocks": best_key,
            "swept": swept,
            "device_kind": dev_kind,
        }
        rec.update(session_stats(
            rec["metric"], rec["value"], {"device_kind": dev_kind}
        ))
        emit(rec)

    return 1 if failures else 0


def _commit_replicated(params, mesh):
    """device_put params replicated-committed on the mesh BEFORE a
    donated jit loop: init_lm's uncommitted arrays compile one program
    and the donated (committed) output compiles a SECOND — a hidden
    first-launch-sized stall inside timed launch 0 (observed 4.4s vs
    0.06s steady on CPU, launch_spread 70-120x)."""
    import jax
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as _P

    return jax.device_put(params, NamedSharding(mesh, _P()))


class _SkipCaptured(Exception):
    """Raised inside a capture section whose metrics are all fresh —
    caught by the section's own except and silently skipped."""


def _iter_log_records(path):
    """Yield (section_epoch_ts, record_dict) for every parseable JSON
    metric line in the append-only log. ONE parser for both freshness
    (_fresh_capture) and median pooling (session_stats) so the two can
    never drift on format details."""
    cur_ts = 0.0
    try:
        with open(path) as f:
            for ln in f:
                if ln.startswith("## "):
                    try:
                        cur_ts = time.mktime(time.strptime(
                            ln[3:22], "%Y-%m-%d %H:%M:%S"
                        ))
                    except ValueError:
                        pass
                    continue
                if not ln.startswith('{"metric"'):
                    continue
                try:
                    yield cur_ts, json.loads(ln)
                except ValueError:
                    continue  # half-written tail line
    except OSError:
        return


def _chip_success(d: dict) -> bool:
    """ONE definition of "successful on-chip capture" shared by
    _fresh_capture and script/summarize_evidence.py: value > 0, no
    error, a non-cpu device_kind (smoke runs append to the same log),
    not diff_noisy (a deliberately deflated conservative number), not
    exceeds_physical_peak (a self-declared broken HBM derivation must
    be re-measured, not skipped-as-fresh for 24h), and every numeric
    field finite (a speculative capture with target_loss=NaN is a
    degenerate model, not evidence — observed 2026-08-02 04:36)."""
    import math

    return (
        isinstance(d.get("value"), (int, float))
        and d["value"] > 0
        and "error" not in d
        and d.get("device_kind") not in (None, "cpu")
        and d.get("diff_noisy") is not True
        and d.get("exceeds_physical_peak") is not True
        and all(
            math.isfinite(v)
            for v in d.values()
            if isinstance(v, float)
        )
    )


def _fresh_capture(metric: str, within_s: "float | None" = None) -> bool:
    """True when BENCH_ONCHIP.md already holds a SUCCESSFUL on-chip
    capture of ``metric`` newer than ``within_s``. Retry resumption: a
    task that wedged at mode k must not re-pay modes 1..k-1 against
    its attempt budget and a flaky tunnel window — it skips straight
    to the open modes; next-round reruns still happen because captures
    age out.

    "Successful on-chip" is strict: value > 0, no error field, a
    non-cpu device_kind (a smoke watcher run appends cpu lines to the
    SAME log — they must never satisfy a chip task), and not
    diff_noisy (a deliberately deflated conservative number should be
    retried for a clean sample while budget remains).

    ``within_s`` defaults to 24h; PS_ONCHIP_FRESH_S overrides it for
    an interactive re-capture pass — e.g. after an optimization lands
    mid-day, yesterday's freshness window would otherwise hide the
    change from every task until tomorrow."""
    if within_s is None:
        raw = os.environ.get("PS_ONCHIP_FRESH_S", "")
        try:
            within_s = float(raw) if raw else 86400.0
        except ValueError:
            raise SystemExit(
                f"PS_ONCHIP_FRESH_S must be seconds (a number), "
                f"got {raw!r}"
            )
    for ts, d in _iter_log_records(LOG_MD):
        if (
            d.get("metric") == metric
            and _chip_success(d)
            and time.time() - ts < within_s
        ):
            return True
    return False


def _lm_base() -> dict:
    """The byte-LM base shape shared by task_lm and task_serve. ONE
    definition on purpose: serve metrics pool session_stats medians
    with prior captures keyed on these shapes, so the two tasks
    drifting apart would silently split the cross-round history."""
    base = dict(
        vocab=256, d_model=512, n_heads=8, n_layers=8, d_ff=2048,
        remat=True, compute_dtype="bfloat16",
    )
    if SMOKE:
        base.update(d_model=64, n_heads=2, n_layers=2, d_ff=128)
    return base


def _mfu_modes(base: dict) -> list:
    """The queued MFU-push mode list as (name, LMConfig kwargs,
    {seq, batch, spl} overrides). Module-level ON PURPOSE:
    tests/test_lm_app.py trace+lowers the EXACT queued shapes from
    this one definition — these configs have never executed anywhere
    (smoke shrinks shapes) and drift between task and test would void
    that protection (same reasoning as _lm_base).

    d1024: d_head 128 (n_heads 8), seq 4096 with the token count kept
    via batch 8 — attention drops to ~1/4 of step FLOPs; the noremat
    variant removes recompute (MFU counts USEFUL flops, so remat
    deflates it ~25-30%), b4 keeps activations ~2 GB. d2048 (~400M
    params, d_ff 8192): attention falls to ~1/6 of step FLOPs, so the
    matmul share — the MXU's home turf — sets MFU almost alone; SGD +
    donation keeps params+grads at 1.6 GB transient. The s2048
    variant halves the attention share again at the same tokens/step
    — insurance against the flash kernel underperforming at mid
    sequence lengths (the 04:27 capture showed s=4096 flash at 1/3
    the s=8192 rate).

    The two _noremat push variants (added after the 08-02 captures
    landed d1024_s4096_noremat at 53.9% MFU, the round's best) apply
    the same remat removal to the shapes that measured best with it:
    d2048_s2048 (51.4% WITH remat; ~3.6 GB noremat activations at
    batch 4 + 1.6 GB params/grads fits comfortably) and d1024_s2048
    at batch 8 (halved attention share AND doubled rows vs the 53.9%
    capture; ~6.5 GB activations + 1.2 GB params)."""
    big = {**base, "d_model": 1024, "n_layers": 12, "d_ff": 4096}
    d2048 = {**base, "d_model": 2048, "n_heads": 16, "n_layers": 8,
             "d_ff": 8192}
    return [
        ("mfu_d1024_s4096", dict(attention="ring_flash", **big),
         {"seq": 4096, "batch": 8}),
        ("mfu_d1024_s4096_noremat",
         dict(attention="ring_flash", **{**big, "remat": False}),
         {"seq": 4096, "batch": 4}),
        ("mfu_d2048_s4096", dict(attention="ring_flash", **d2048),
         {"seq": 4096, "batch": 4, "spl": 4}),
        ("mfu_d2048_s2048", dict(attention="ring_flash", **d2048),
         {"seq": 2048, "batch": 8, "spl": 4}),
        ("mfu_d2048_s2048_noremat",
         dict(attention="ring_flash", **{**d2048, "remat": False}),
         {"seq": 2048, "batch": 4, "spl": 4}),
        ("mfu_d1024_s2048_noremat_b8",
         dict(attention="ring_flash", **{**big, "remat": False}),
         {"seq": 2048, "batch": 8}),
    ]


def task_lm() -> int:
    """Byte-LM train step on one chip at seq 8192: tokens/s + MFU for
    each attention mode (VERDICT r2 item 4)."""
    import jax
    import numpy as np

    from parameter_server_tpu.models.transformer import (
        LMConfig,
        init_lm,
        make_lm_train_step,
        shard_tokens,
    )
    from parameter_server_tpu.system.postoffice import Postoffice

    Postoffice.reset()
    po = Postoffice.instance().start()
    mesh = po.mesh

    # per-mode seq/batch/spl defaults live in the mode loop (ov.get);
    # scan-fused supersteps (make_lm_train_step(steps_per_launch=)):
    # identical training semantics to spl separate calls, minus the
    # per-step dispatch round trip that dominates through the tunnel
    # (~0.3s/launch — the linear bench's T lever, applied to the LM)
    base = _lm_base()
    big = dict(base)
    if not SMOKE:  # ~100M params: MFU at a size where matmuls dominate
        big.update(d_model=1024, n_layers=12, d_ff=4096)
    # third element: per-mode shape overrides {seq, batch, spl} — the
    # MFU-push configs trade sequence length for batch (halving the
    # attention share of the FLOPs, which runs at ~10% of peak in the
    # flash kernel, so the matmul share sets the MFU ceiling)
    modes = [
        ("ring", LMConfig(attention="ring", **base), {}),
        ("ring_flash", LMConfig(attention="ring_flash", **base), {}),
        ("ring_flash_rope",
         LMConfig(attention="ring_flash", rope=True, **base), {}),
        ("ring_flash_w1024",
         LMConfig(attention="ring_flash",
                  window=64 if SMOKE else 1024, **base), {}),
    ]
    if not SMOKE:  # big == base under SMOKE: skip the duplicate metric
        # h4: same d_model/params, d_head 128 instead of 64 — the
        # end-to-end readout of the flash task's d_head sweep (deeper
        # MXU reduction per head)
        modes.append(
            ("ring_flash_h4",
             LMConfig(attention="ring_flash", **{**base, "n_heads": 4}), {})
        )
        modes.append(
            ("ring_flash_d1024", LMConfig(attention="ring_flash", **big), {})
        )
        # the MFU headline configs (r3 verdict item 2: capture a
        # >=100M-param MFU and push toward 15%+) — shapes live in
        # _mfu_modes, shared with the CI trace+lower test so the
        # queued configs can never drift unvalidated
        for mname, mkw, mov in _mfu_modes(base):
            modes.append((mname, LMConfig(**mkw), mov))
    rng = np.random.default_rng(0)

    dev = jax.devices()[0]
    peak = PEAK_BF16.get(dev.device_kind)
    # FLOPs per step: 6*P*T matmul + attention 12*L*H*S^2*dh (fwd+bwd,
    # causal halves it)
    skipped_fresh = []
    for name, cfg, ov in modes:
        if not SMOKE and _fresh_capture(f"lm_train_{name}"):
            skipped_fresh.append(name)
            continue  # retry resumption: this mode already landed
        try:
            seq = ov.get("seq", 256 if SMOKE else 8192)
            batch = ov.get("batch", 2 if SMOKE else 4)
            spl = ov.get("spl", 2 if SMOKE else 8)
            # fresh seeded rng per mode: equal-shape modes must train on
            # IDENTICAL tokens so their emitted losses stay comparable
            # (a flash numerics regression shows as loss divergence
            # from ring, not as data variation)
            tokens = np.random.default_rng(0).integers(
                0, 256, (spl, batch, seq), np.int32
            )
            params = _commit_replicated(
                init_lm(jax.random.PRNGKey(0), cfg), mesh
            )
            # donate: this loop always rebinds params (halves footprint)
            step = make_lm_train_step(
                cfg, mesh, donate=True, steps_per_launch=spl
            )
            toks = shard_tokens(tokens, mesh)
            t0 = time.perf_counter()
            params, loss = step(params, toks)
            _flush(loss)
            first_launch_s = time.perf_counter() - t0
            n = 3  # launches; spl fused steps each — each timed and
            # flushed separately so the record carries a median +
            # spread instead of one variance-blind mean (r3 weak #8)
            launch_secs = []
            for _ in range(n):
                t0 = time.perf_counter()
                params, loss = step(params, toks)
                _flush(loss)
                launch_secs.append(time.perf_counter() - t0)
            launch_secs.sort()
            sec = launch_secs[n // 2] / spl
            launch_spread = (
                (launch_secs[-1] - launch_secs[0]) / launch_secs[n // 2]
            )
            # the first launch = compile + spl executed steps; back the
            # execution out so compile_s stays comparable across records
            compile_s = max(0.0, first_launch_s - sec * spl)
            loss = loss[-1]  # scan returns per-step losses
            n_params = sum(x.size for x in jax.tree.leaves(params))
            ntok = batch * seq
            matmul_flops = 6.0 * n_params * ntok
            # attended pairs: causal full = S^2/2; sliding window = each
            # query sees ~min(window, pos) keys = S*w - w^2/2 exactly
            w = min(cfg.window or seq, seq)
            pairs = seq * w - w * w / 2.0
            attn_flops = (
                12.0 * cfg.n_layers * batch * cfg.n_heads
                * pairs * (cfg.d_model // cfg.n_heads)
            )
            flops = matmul_flops + attn_flops
            rec = {
                "metric": f"lm_train_{name}",
                "value": round(ntok / sec, 1),
                "unit": "tokens/sec",
                "seq": seq,
                "batch": batch,
                "steps_per_launch": spl,
                "n_params": int(n_params),
                "step_ms": round(sec * 1e3, 2),
                "launch_spread": round(launch_spread, 3),
                "compile_s": round(compile_s, 1),
                "loss": round(float(loss), 4),
                "device_kind": dev.device_kind,
            }
            if peak:
                rec["mfu"] = round(flops / sec / peak, 4)
            rec.update(session_stats(
                rec["metric"], rec["value"],
                {"device_kind": rec["device_kind"], "seq": seq,
                 "batch": batch, "n_params": rec["n_params"]},
            ))
            emit(rec)
        except Exception as e:  # keep going: one mode failing is evidence too
            emit({"metric": f"lm_train_{name}", "error": repr(e)[:500]})

    if skipped_fresh:
        emit({"metric": "lm_task_resume", "value": len(skipped_fresh),
              "unit": "modes_skipped_fresh", "skipped": skipped_fresh})
    return 0


def task_serve() -> int:
    """Serving-path captures on one chip: KV-cached decode (MHA vs GQA
    vs GQA+int8 cache) with physically-checked HBM accounting, beam
    search stepping cost, and speculative-decoding speedup with a
    TRAINED draft (r3 verdict items 3 and 56s). Split from task_lm so
    a tunnel wedge mid-train cannot cost the serving evidence and
    vice versa."""
    import jax
    import numpy as np

    from parameter_server_tpu.models.transformer import (
        LMConfig,
        init_lm,
        make_lm_train_step,
        shard_tokens,
    )
    from parameter_server_tpu.system.postoffice import Postoffice

    Postoffice.reset()
    po = Postoffice.instance().start()
    mesh = po.mesh

    # the same shapes task_lm's decode section measured historically,
    # so serve metrics stay comparable across rounds (_lm_base is the
    # single shared definition)
    base = _lm_base()
    base_cfg = LMConfig(attention="ring", **base)
    dev = jax.devices()[0]

    # KV-cached decode throughput (the serving path): prefill a prompt,
    # then time pure generation tokens/s. Decode is bandwidth-bound
    # (weights re-read per token), so report achieved GB/s vs HBM peak
    # alongside raw tokens/s.
    import dataclasses as _dc

    import jax.numpy as jnp

    from parameter_server_tpu.models.transformer import lm_generate

    b, prefill, steps = (2, 32, 16) if SMOKE else (8, 2048, 256)
    # "" = the base (MHA) config; the grouped variant shrinks the KV
    # cache (quartered when n_heads allows, else MQA) — its decode
    # speedup vs base is the on-chip evidence for GQA serving
    kvh = base_cfg.n_heads // 4 if base_cfg.n_heads % 4 == 0 else 1
    decode_cfgs = [
        ("", base_cfg),
        (f"_kv{kvh}", _dc.replace(base_cfg, n_kv_heads=kvh)),
        # int8 cache on top of GQA: the cache is the dominant decode
        # traffic once GQA narrows the weights, so quantizing it is the
        # next serving lever — measure it where it matters
        (f"_kv{kvh}_i8",
         _dc.replace(base_cfg, n_kv_heads=kvh, kv_cache_dtype="int8")),
    ]
    skipped_fresh = []
    for di, (tag, cfg) in enumerate(decode_cfgs):
        if not SMOKE and _fresh_capture(f"lm_decode_tokens_per_sec{tag}"):
            skipped_fresh.append(f"decode{tag}")
            continue  # retry resumption
        try:
            params = init_lm(jax.random.PRNGKey(0), cfg)
            # per-section seed: resumption may SKIP earlier modes, so
            # sharing one rng stream would hand this mode different
            # prompt bytes depending on which modes were fresh —
            # breaking cross-round comparability of the medians
            prompt = jnp.asarray(
                np.random.default_rng(100 + di).integers(
                    0, 256, (b, prefill), np.int32
                )
            )

            def timed(s, params=params, prompt=prompt, cfg=cfg):
                # the FIRST call (the compiling one) is what compile_s
                # times; then median of k FULL-ARRAY fetches. The flush
                # fetches the ENTIRE token output (tens of KB —
                # negligible transfer), not one element: a
                # single-element fetch through the tunnel has
                # under-waited before (SURVEY measurement-integrity
                # note), and an under-measured decode_sec is exactly
                # how round 3 published a physically impossible GB/s
                t0 = time.perf_counter()
                np.asarray(lm_generate(params, prompt, cfg, steps=s))
                comp = time.perf_counter() - t0
                k = 5
                ts = []
                for _ in range(k):
                    t0 = time.perf_counter()
                    np.asarray(lm_generate(params, prompt, cfg, steps=s))
                    ts.append(time.perf_counter() - t0)
                ts.sort()
                med = ts[k // 2]
                spread = (ts[-1] - ts[0]) / med if med else 0.0
                # the compiling call also executes once: back that out
                comp = max(0.0, comp - med)
                return med, comp, round(spread, 3)

            # generation is batched-prefill (one causal forward) + a
            # scan of single-token decode iterations; differencing two
            # step counts isolates PURE decode, and the steps~=1 run is
            # the time-to-first-token serving latency
            sec_short, comp_short, spread_short = timed(1)
            sec_long, comp_long, spread_long = timed(steps)
            decode_sec = sec_long - sec_short
            diff_noisy = decode_sec < 0.2 * sec_long  # noise floor
            if diff_noisy:  # conservative: charge the whole call
                decode_sec = sec_long
            decode_tok_s = b * (steps - 1) / decode_sec
            n_params = sum(x.size for x in jax.tree.leaves(params))
            # Per decode iteration the chip re-reads the weights at
            # COMPUTE width (the f32→bf16 cast of loop-invariant
            # params is hoisted out of the decode scan, so the scan
            # body streams bf16 copies — counting stored f32 width
            # here double-counted weight traffic in round 3), plus the
            # FULL allocated KV cache (the dense masked einsum reads
            # every position of the static-shape cache each step, so
            # allocation length — not attended length — is the read),
            # plus the one-position cache write.
            hd = cfg.d_model // cfg.n_heads
            total_len = prefill + steps
            comp_width = 2.0 if cfg.compute_dtype == "bfloat16" else 4.0
            if cfg.kv_cache_dtype == "int8":
                # 1 byte/element + one f32 scale per hd-row
                cache_width = 1.0 + 4.0 / hd
            else:
                cache_width = comp_width
            param_read = n_params * comp_width
            cache_read = (
                2 * cfg.n_layers * b * cfg.kv_heads * total_len * hd
                * cache_width
            )
            cache_write = 2 * cfg.n_layers * b * cfg.kv_heads * hd * cache_width
            per_step_bytes = param_read + cache_read + cache_write
            hbm_gb_s = per_step_bytes * (steps - 1) / decode_sec / 1e9
            rec = {
                "metric": f"lm_decode_tokens_per_sec{tag}",
                "value": round(decode_tok_s, 1),
                "unit": "tokens/sec",
                "batch": b, "prefill": prefill, "steps": steps,
                "n_kv_heads": cfg.kv_heads,
                "prefill_plus_first_token_ms": round(sec_short * 1e3, 1),
                "diff_noisy": diff_noisy,
                "timing_reps": 5,
                "timing_spread": [spread_short, spread_long],
                "n_params": int(n_params),
                "param_read_bytes_per_step": int(param_read),
                "kv_cache_read_bytes_per_step": int(cache_read),
                "hbm_gb_s": round(hbm_gb_s, 2),
                "compile_s": round(comp_short + comp_long, 1),
                "device_kind": dev.device_kind,
            }
            rec.update(session_stats(
                rec["metric"], rec["value"],
                # diff_noisy priors charged the WHOLE call as decode
                # time — a deflated number that must not pool into the
                # clean-capture median
                {"device_kind": rec["device_kind"], "batch": b,
                 "prefill": prefill, "steps": steps,
                 "diff_noisy": False},
            ))
            peak_hbm = PEAK_HBM_GB_S.get(dev.device_kind)
            if peak_hbm:
                rec["hbm_frac_of_peak"] = round(hbm_gb_s / peak_hbm, 3)
                if hbm_gb_s > peak_hbm:
                    # impossible rate: publish the flag AND the
                    # whole-call conservative rate instead of letting
                    # the reader trust a broken derivation
                    rec["exceeds_physical_peak"] = True
                    rec["hbm_gb_s_conservative"] = round(
                        per_step_bytes * (steps - 1) / sec_long / 1e9, 2
                    )
            emit(rec)
        except Exception as e:
            emit({
                "metric": f"lm_decode_tokens_per_sec{tag}",
                "error": repr(e)[:500],
            })

    # beam search: the serving mode whose per-step cost ADDS the cache
    # parent-gather to the decode step — price it against plain decode
    # at the same batch of sequences (W x the rows, so tok/s here is
    # sequences-completed x steps, not raw row-tokens)
    try:
        from parameter_server_tpu.models.transformer import lm_beam_search

        bw = 4
        if not SMOKE and _fresh_capture(f"lm_beam_search_w{bw}"):
            raise _SkipCaptured
        bcfg = _dc.replace(base_cfg, n_kv_heads=kvh)
        bparams = init_lm(jax.random.PRNGKey(0), bcfg)
        bprompt = jnp.asarray(
            np.random.default_rng(3).integers(0, 256, (b, prefill), np.int32)
        )
        bsteps = 8 if SMOKE else 128
        # same differencing discipline as the decode metric: a 1-step
        # and a bsteps run share the prefill + tiling cost, so the
        # difference isolates PURE beam stepping — the number the
        # "compare with plain decode" note needs (the baseline is
        # differenced the same way)
        def beam_timed(ns):
            t0 = time.perf_counter()
            np.asarray(lm_beam_search(bparams, bprompt, bcfg, steps=ns,
                                      beam_width=bw)[0])
            return time.perf_counter() - t0

        beam_timed(1)       # compile short program
        beam_timed(bsteps)  # compile long program
        sec_short = beam_timed(1)
        sec_long = beam_timed(bsteps)
        beam_sec = sec_long - sec_short
        noisy = beam_sec < 0.2 * sec_long
        if noisy:
            beam_sec = sec_long  # conservative: charge the whole call
        rec = {
            "metric": f"lm_beam_search_w{bw}",
            "value": round(b * (bsteps - 1) / beam_sec, 1),
            "unit": "sequences*steps/sec",
            "batch": b, "prefill": prefill, "steps": bsteps,
            "beam_width": bw, "diff_noisy": noisy,
            "note": "differenced like lm_decode_tokens_per_sec_kv* "
            "(prefill+tiling excluded); per-step cost includes the "
            "W-way cache parent-gather",
            "device_kind": dev.device_kind,
        }
        rec.update(session_stats(
            rec["metric"], rec["value"],
            {"device_kind": rec["device_kind"], "batch": b,
             "prefill": prefill, "steps": bsteps},
        ))
        emit(rec)
    except _SkipCaptured:
        skipped_fresh.append("beam")
    except Exception as e:
        emit({"metric": "lm_beam_search_w4", "error": repr(e)[:400]})

    # Speculative decoding: rounds replace per-token target passes.
    # A speed claim needs a draft whose proposals the target ACCEPTS —
    # two random-init models give degenerate acceptance and prove
    # nothing (round-3 verdict: "a speed feature with zero measured
    # speedup"). So: quick-train the target AND a ~4x-narrower draft
    # on the same structured byte corpus (noisy periodic text — the
    # draft learns most of the structure, acceptance lands high but
    # below 1), then sweep gamma and report tok/s, accepted_frac and
    # speedup vs the SAME trained target decoding plainly. The
    # draft==target run at gamma=4 isolates chunk-verify overhead
    # (its speedup ceiling is 1.0 by construction — same-size draft).
    try:
        from parameter_server_tpu.models.speculative import (
            speculative_generate,
        )

        if not SMOKE and all(
            _fresh_capture(f"lm_decode_speculative_{t}_g{g}")
            for t, g in (("upper", 4), ("draft4x", 2), ("draft4x", 4),
                         ("draft4x", 8))
        ):
            raise _SkipCaptured

        tcfg = _dc.replace(base_cfg, n_kv_heads=kvh)
        dcfg = LMConfig(
            vocab=256,
            d_model=tcfg.d_model // 4,
            n_heads=max(1, tcfg.n_heads // 4),
            n_layers=2,
            d_ff=tcfg.d_ff // 4,
            compute_dtype=tcfg.compute_dtype,
            n_kv_heads=None,
        )
        # structured corpus: period-16 byte pattern + 10% uniform noise.
        # Own seeded stream (not the shared rng): resumption can skip
        # the decode modes before this section, and the corpus/training
        # draws must be identical either way
        srng = np.random.default_rng(7)
        pat = np.tile(np.arange(97, 113, dtype=np.int32), 1 << 14)
        noise = srng.integers(0, 256, pat.size, np.int32)
        corpus = np.where(srng.random(pat.size) < 0.1, noise, pat)
        train_seq, train_steps = (64, 4) if SMOKE else (512, 120)
        # shard_tokens shards the [B, S] token width over the data
        # axis: S = train_seq+1 must divide it (the 8-device CPU smoke
        # mesh rejected width 65; the single-chip mesh never trips)
        n_data = mesh.shape.get("data", 1)
        train_seq = max(n_data, (train_seq + 1) // n_data * n_data) - 1
        trained = {}
        for nm, cfg_i in (("target", tcfg), ("draft", dcfg)):
            p_i = _commit_replicated(
                init_lm(jax.random.PRNGKey(0 if nm == "target" else 7),
                        cfg_i),
                mesh,
            )
            step_i = make_lm_train_step(cfg_i, mesh, donate=True)
            for it in range(train_steps):
                starts = srng.integers(
                    0, corpus.size - train_seq - 1, 8)
                toks = np.stack(
                    [corpus[s:s + train_seq + 1] for s in starts]
                )
                p_i, tl = step_i(p_i, shard_tokens(toks, mesh))
            _flush(tl)
            trained[nm] = (p_i, float(tl))
        tparams, tloss = trained["target"]
        dparams, dloss = trained["draft"]
        sp, ssteps = (8, 8) if SMOKE else (256, 256)
        prompt = jnp.asarray(
            np.stack([corpus[s:s + sp] for s in
                      srng.integers(0, corpus.size - sp, b)])
        )
        # median-of-k discipline (_med_time): the headline speedup
        # must not rest on two single-shot timings (a GC pause or
        # tunnel hiccup in either leg skews every ratio)
        np.asarray(lm_generate(tparams, prompt, tcfg, steps=ssteps))
        plain_sec, _ = _med_time(
            lambda: np.asarray(lm_generate(tparams, prompt, tcfg,
                                           steps=ssteps))
        )
        runs = [("upper", tparams, tcfg, [4])]
        runs.append(("draft4x", dparams, dcfg, [2] if SMOKE else [2, 4, 8]))
        for stag, dp, dc, gammas in runs:
            for gamma in gammas:

                def spec_once(dp=dp, dc=dc, gamma=gamma):
                    out, st = speculative_generate(
                        tparams, tcfg, dp, dc, prompt, steps=ssteps,
                        gamma=gamma, return_stats=True,
                    )
                    np.asarray(out)
                    return st

                t0 = time.perf_counter()
                spec_once()
                compile_s = time.perf_counter() - t0
                sec, st = _med_time(spec_once)
                compile_s = max(0.0, compile_s - sec)
                emit({
                    "metric": f"lm_decode_speculative_{stag}_g{gamma}",
                    "value": round(b * ssteps / sec, 1),
                    "unit": "tokens/sec",
                    "batch": b, "prefill": sp, "steps": ssteps,
                    "gamma": gamma,
                    "trained_steps": train_steps,
                    "target_loss": round(tloss, 3),
                    "draft_loss": round(dloss, 3),
                    "plain_tokens_per_sec": round(b * ssteps / plain_sec, 1),
                    "speedup_vs_plain": round(plain_sec / sec, 2),
                    "rounds": int(st["rounds"]),
                    "accepted_frac": round(float(st["accepted_frac"]), 3),
                    "compile_s": round(compile_s, 1),
                    "device_kind": dev.device_kind,
                })
    except _SkipCaptured:
        skipped_fresh.append("speculative")
    except Exception as e:
        emit({"metric": "lm_decode_speculative", "error": repr(e)[:500]})

    # Bandwidth-bound speculative variant (r5): the toy sweep above is
    # per-step OVERHEAD-bound — at 25M params a decode step costs
    # ~0.4 ms of fixed per-step work, the 16x-smaller draft pays the
    # same fixed cost, and even accepted_frac=1.0 measured 1.05x.
    # Speculation's actual claim is about WEIGHT-BANDWIDTH-bound
    # decode: at d1024 (~151M params, ~300 MB of bf16 weights re-read
    # per token) a draft step is genuinely ~10x cheaper and the
    # (gamma+1)-wide verify reads the target weights ONCE per round.
    # Same corpus family and training discipline as the toy sweep;
    # fully self-contained so resumption can skip either section
    # independently.
    try:
        from parameter_server_tpu.models.speculative import (
            speculative_generate,
        )

        if SMOKE:
            raise _SkipCaptured  # the toy sweep covers the code path
        if all(_fresh_capture(f"lm_decode_speculative_bw_g{g}")
               for g in (4, 8)):
            raise _SkipCaptured
        bw_t = LMConfig(vocab=256, d_model=1024, n_heads=8, n_layers=8,
                        d_ff=4096, remat=True, compute_dtype="bfloat16",
                        n_kv_heads=2, attention="ring")
        # the draft's enemy is per-step OP-DISPATCH overhead, not
        # FLOPs (first capture: a 4M-param draft step cost 0.34 ms vs
        # the 88M target's 0.49 — dispatch-bound, speedup 1.1x): ONE
        # layer halves the op count, and batch 32 (below) amortizes
        # per-op cost over 4x the rows
        bw_d = LMConfig(vocab=256, d_model=256, n_heads=2, n_layers=1,
                        d_ff=1024, remat=True, compute_dtype="bfloat16")
        brng = np.random.default_rng(11)
        bcorpus = _spec_corpus(brng)
        bw_seq, bw_train_steps = 512, 160
        n_data = mesh.shape.get("data", 1)
        bw_seq = max(n_data, (bw_seq + 1) // n_data * n_data) - 1
        # lr per width: plain-SGD 0.3 (the toy pair's default) DIVERGES
        # at d1024 — the first bw capture came back target_loss=NaN,
        # accepted_frac=0.0 (BENCH_ONCHIP 2026-08-02 04:36) — so the
        # wide target trains at 0.1
        bw_trained = _train_spec_pair(
            mesh, bcorpus, brng,
            (("target", bw_t, 0.1, bw_train_steps),
             ("draft", bw_d, 0.3, bw_train_steps)),
            bw_seq,
        )
        bw_tp, bw_tloss = bw_trained["target"]
        bw_dp, bw_dloss = bw_trained["draft"]
        bw_b, bw_sp, bw_steps = 32, 256, 256
        bw_prompt = jnp.asarray(
            np.stack([bcorpus[s:s + bw_sp] for s in
                      brng.integers(0, bcorpus.size - bw_sp, bw_b)])
        )

        np.asarray(lm_generate(bw_tp, bw_prompt, bw_t, steps=bw_steps))
        bw_plain_sec, _ = _med_time(
            lambda: np.asarray(
                lm_generate(bw_tp, bw_prompt, bw_t, steps=bw_steps)
            )
        )
        bw_nparams = sum(x.size for x in jax.tree.leaves(bw_tp))
        for gamma in (4, 8):

            def bw_spec(gamma=gamma):
                out, st = speculative_generate(
                    bw_tp, bw_t, bw_dp, bw_d, bw_prompt, steps=bw_steps,
                    gamma=gamma, return_stats=True,
                )
                np.asarray(out)
                return st

            t0 = time.perf_counter()
            bw_spec()
            compile_s = time.perf_counter() - t0
            sec, st = _med_time(bw_spec)
            compile_s = max(0.0, compile_s - sec)
            emit({
                "metric": f"lm_decode_speculative_bw_g{gamma}",
                "value": round(bw_b * bw_steps / sec, 1),
                "unit": "tokens/sec",
                "batch": bw_b, "prefill": bw_sp, "steps": bw_steps,
                "gamma": gamma, "n_params": int(bw_nparams),
                "trained_steps": bw_train_steps,
                "target_loss": round(bw_tloss, 3),
                "draft_loss": round(bw_dloss, 3),
                "plain_tokens_per_sec": round(
                    bw_b * bw_steps / bw_plain_sec, 1),
                "speedup_vs_plain": round(bw_plain_sec / sec, 2),
                "rounds": int(st["rounds"]),
                "accepted_frac": round(float(st["accepted_frac"]), 3),
                "compile_s": round(compile_s, 1),
                "device_kind": dev.device_kind,
            })
    except _SkipCaptured:
        # SMOKE skips are not "fresh capture existed" — only record a
        # resume skip when a real capture made the guard fire
        if not SMOKE:
            skipped_fresh.append("speculative_bw")
    except Exception as e:
        emit({"metric": "lm_decode_speculative_bw",
              "error": repr(e)[:500]})
    if skipped_fresh:
        emit({"metric": "serve_task_resume", "value": len(skipped_fresh),
              "unit": "sections_skipped_fresh", "skipped": skipped_fresh})
    return 0


def _spec_corpus(rng):
    """Structured byte corpus shared by every speculative bench: a
    16-byte cycle with 10% uniform noise — regular enough that a tiny
    draft tracks the target, noisy enough that losses stay
    informative. ONE definition so the bw and big benches stay
    comparable."""
    import numpy as np

    pat = np.tile(np.arange(97, 113, dtype=np.int32), 1 << 14)
    noise = rng.integers(0, 256, pat.size, np.int32)
    return np.where(rng.random(pat.size) < 0.1, noise, pat)


def _med_time(fn, k=3):
    """(median seconds, last result) over k calls of fn."""
    ts = []
    r = None
    for _ in range(k):
        t0 = time.perf_counter()
        r = fn()
        ts.append(time.perf_counter() - t0)
    ts.sort()
    return ts[k // 2], r


def _train_spec_pair(mesh, corpus, rng, pairs, seq):
    """Train the (target, draft) model pair for a speculative bench:
    ``pairs`` is ((name, LMConfig, lr, steps), ...); returns
    {name: (params, loss)}. Raises on a non-finite loss — no speedup
    claim can rest on a degenerate model (the first bw capture came
    back target_loss=NaN, accepted_frac=0.0, BENCH_ONCHIP 08-02
    04:36; lr-per-width is the caller's fix for that)."""
    import jax
    import numpy as np

    from parameter_server_tpu.models.transformer import (
        init_lm,
        make_lm_train_step,
        shard_tokens,
    )

    out = {}
    for nm, cfg_i, lr_i, nst in pairs:
        p_i = _commit_replicated(
            init_lm(jax.random.PRNGKey(1 if nm == "target" else 8),
                    cfg_i),
            mesh,
        )
        step_i = make_lm_train_step(cfg_i, mesh, donate=True, lr=lr_i)
        tl = None
        for _ in range(nst):
            starts = rng.integers(0, corpus.size - seq - 1, 8)
            toks = np.stack([corpus[s:s + seq + 1] for s in starts])
            p_i, tl = step_i(p_i, shard_tokens(toks, mesh))
        _flush(tl)
        if not np.isfinite(float(tl)):
            raise RuntimeError(
                f"speculative {nm} training diverged "
                f"(loss={float(tl)})"
            )
        out[nm] = (p_i, float(tl))
    return out


def task_spec_big() -> int:
    """Speculative decoding at the scale where it actually pays.

    The two prior speculative benches measured the true NEGATIVE
    result: at 25M and even 88M target params both draft and target
    steps are op-DISPATCH-bound (~0.3-0.7 ms fixed), so the draft is
    never much cheaper than the target and speedup caps near 1.1x
    even at accepted_frac 1.0 (BENCH_ONCHIP 08-02 04:14, 06:31).
    Speculation's production claim is about WEIGHT-BANDWIDTH-bound
    targets (Leviathan et al.): here the target is 860M params
    (n_kv_heads=2 shrinks the K/V projections below the naive
    4*d^2-per-layer count; ~1.7 GB of bf16 weights re-read per token,
    ~2.1 ms/step at the v5e's ~819 GB/s), the draft stays the
    dispatch-floor 4M 1-layer model, so the draft/target cost ratio
    finally drops to ~0.1 and the (gamma+1)-wide verify reads the
    target weights ONCE per round. Own task (not a serve section):
    training peaks ~8 GB (f32 params + donated grads) and a fresh
    process guarantees the HBM is clean of the serve task's caches.
    Captured 08-02 06:48: 2.33x at gamma=8, accepted 0.978."""
    import jax
    import numpy as np

    from parameter_server_tpu.models.speculative import (
        speculative_generate,
    )
    from parameter_server_tpu.models.transformer import (
        LMConfig,
        lm_generate,
    )
    from parameter_server_tpu.system.postoffice import Postoffice

    dev = jax.devices()[0]
    if dev.platform != "tpu" and not SMOKE:
        emit({"metric": "spec_big_onchip", "error": "not on tpu"})
        return 1
    if not SMOKE and all(_fresh_capture(f"lm_decode_speculative_big_g{g}")
                         for g in (4, 8)):
        emit({"metric": "spec_big_task_resume", "value": 2,
              "unit": "sections_skipped_fresh"})
        return 0
    Postoffice.reset()
    po = Postoffice.instance().start()
    mesh = po.mesh

    if SMOKE:
        tgt = LMConfig(vocab=256, d_model=64, n_heads=2, n_layers=2,
                       d_ff=128, remat=True, compute_dtype="bfloat16")
        steps_train_t = 4
    else:
        # 860M params: 20 x (attn w/ 2 KV heads + 2*2048*8192 mlp)
        tgt = LMConfig(vocab=256, d_model=2048, n_heads=16,
                       n_kv_heads=2, n_layers=20, d_ff=8192,
                       remat=True, compute_dtype="bfloat16")
        steps_train_t = 80
    drf = LMConfig(vocab=256, d_model=64 if SMOKE else 256,
                   n_heads=2, n_layers=1, d_ff=128 if SMOKE else 1024,
                   remat=True, compute_dtype="bfloat16")
    rng = np.random.default_rng(11)
    corpus = _spec_corpus(rng)
    seq = 128 if SMOKE else 512
    # shard_tokens splits [batch, seq+1] over the data axis: keep
    # seq+1 divisible by it (same adjustment as the bw bench)
    n_data = mesh.shape.get("data", 1)
    seq = max(n_data, (seq + 1) // n_data * n_data) - 1
    try:
        # lr per width as the bw bench: plain-SGD 0.3 diverges past
        # ~d1024, so the wide target trains at 0.05
        trained = _train_spec_pair(
            mesh, corpus, rng,
            (("target", tgt, 0.05, steps_train_t),
             ("draft", drf, 0.3, 4 if SMOKE else 120)),
            seq,
        )
        tp, tloss = trained["target"]
        dp, dloss = trained["draft"]
        b, sp, steps = (2, 16, 16) if SMOKE else (32, 256, 256)
        import jax.numpy as jnp

        prompt = jnp.asarray(np.stack(
            [corpus[s:s + sp] for s in
             rng.integers(0, corpus.size - sp, b)]
        ))

        np.asarray(lm_generate(tp, prompt, tgt, steps=steps))
        plain_sec, _ = _med_time(
            lambda: np.asarray(
                lm_generate(tp, prompt, tgt, steps=steps)
            )
        )
        nparams = sum(x.size for x in jax.tree.leaves(tp))
        for gamma in (4, 8):

            def spec(gamma=gamma):
                out, st = speculative_generate(
                    tp, tgt, dp, drf, prompt, steps=steps,
                    gamma=gamma, return_stats=True,
                )
                np.asarray(out)
                return st

            t0 = time.perf_counter()
            spec()
            compile_s = time.perf_counter() - t0
            sec, st = _med_time(spec)
            compile_s = max(0.0, compile_s - sec)
            emit({
                "metric": f"lm_decode_speculative_big_g{gamma}",
                "value": round(b * steps / sec, 1),
                "unit": "tokens/sec",
                "batch": b, "prefill": sp, "steps": steps,
                "gamma": gamma, "n_params": int(nparams),
                "trained_steps": steps_train_t,
                "target_loss": round(tloss, 3),
                "draft_loss": round(dloss, 3),
                "plain_tokens_per_sec": round(
                    b * steps / plain_sec, 1),
                "speedup_vs_plain": round(plain_sec / sec, 2),
                "rounds": int(st["rounds"]),
                "accepted_frac": round(float(st["accepted_frac"]), 3),
                "compile_s": round(compile_s, 1),
                "device_kind": dev.device_kind,
            })
    except RuntimeError as e:
        # deterministic failure (training divergence): record it and
        # return ok — same seeds would diverge identically, so a
        # watcher retry would only re-burn ~5 min of tunnel budget
        emit({"metric": "lm_decode_speculative_big",
              "error": repr(e)[:500]})
        return 0
    except Exception as e:
        # possibly-transient failure (tunnel flake, OOM race): record
        # and fail so the watcher's attempt budget retries it
        emit({"metric": "lm_decode_speculative_big",
              "error": repr(e)[:500]})
        return 1
    return 0


def task_gatherx() -> int:
    """A/B the gather/scatter formulations that could unthrottle the
    fused step (r3: random gathers ~8ms per 640k indices; the step is
    gather/scatter-bound, and step_phases decomposes but does not
    compare alternatives). Each variant is its own jitted program at
    the headline shapes, timed with the SAME _median_windows + _flush
    discipline as the other tasks (block_until_ready under-waits on
    the tunnel), resumption-gated per variant, with device_kind on
    every record.

    Variants: baseline take-gather; gather from a PRE-SORTED index
    vector (locality sensitivity — sorting cost excluded, so this is
    the upper bound sorting could buy); bf16 and int8 weight-table
    gathers (if gathers are granularity/bandwidth-bound, narrower
    elements should win ~linearly; production pull_quant can then be
    flipped on for real); scatter-add baseline vs sort+segment_sum
    (micro-level twin of the r3 full-path experiment that lost 3x);
    gather+lane-sum at the production matrix layout for direct
    comparison with step_phases."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    dev = jax.devices()[0]
    if dev.platform != "tpu" and not SMOKE:
        # same guard as task_flash: a CPU-fallback run would emit
        # device_kind='cpu' records (which _fresh_capture rightly
        # ignores) yet return 0 — the watcher would mark the task ok
        # and never capture the on-chip numbers
        emit({"metric": "gatherx_onchip", "error": "not on tpu"})
        return 1
    rows, lanes = (256, 8) if SMOKE else (16384, 39)
    n_idx = rows * lanes
    skipped_fresh = []

    def timed(name, fn, *args, scale: float = 1.0):
        """``scale`` converts a measured multi-pass program to a
        per-pass value (e.g. 1/8 for an 8-deep update chain)."""
        if not SMOKE and _fresh_capture(name):
            skipped_fresh.append(name)
            return
        try:
            jf = jax.jit(fn)
            _flush(jf(*args))  # compile untimed
            med, spread = _median_windows(
                lambda: jf(*args), _flush,
                windows=2 if SMOKE else 3, n=2 if SMOKE else 5,
            )
            emit({
                "metric": name,
                "value": round(med * scale * 1e3, 3),
                "unit": "ms",
                "spread": spread,
                "n_idx": n_idx,
                "device_kind": dev.device_kind,
            })
        except Exception as e:
            emit({"metric": name, "error": repr(e)[:300]})

    for logs in ([14] if SMOKE else [22, 26]):
        num_slots = 1 << logs
        tag = f"_s{logs}"
        rng = np.random.default_rng(0)
        # build everything on host, transfer once (no D2H round trips
        # through the tunnel just to sort/quantize)
        idx_np = rng.integers(0, num_slots, n_idx).astype(np.int32)
        w_np = rng.normal(size=num_slots).astype(np.float32)
        g_np = rng.normal(size=n_idx).astype(np.float32)
        idx = jax.device_put(idx_np)
        idx_sorted = jax.device_put(np.sort(idx_np))
        w32 = jax.device_put(w_np)
        w16 = jax.device_put(w_np.astype(jnp.bfloat16))
        w8 = jax.device_put((w_np * 10).astype(np.int8))
        g = jax.device_put(g_np)

        timed(f"gather_f32{tag}", lambda w, i: w[i].sum(), w32, idx)
        timed(f"gather_f32_sorted{tag}",
              lambda w, i: w[i].sum(), w32, idx_sorted)
        timed(f"gather_bf16{tag}",
              lambda w, i: w[i].astype(jnp.float32).sum(), w16, idx)
        timed(f"gather_int8{tag}",
              lambda w, i: (w[i].astype(jnp.float32) * 0.1).sum(),
              w8, idx)
        timed(
            f"scatter_add_f32{tag}",
            lambda i, v: jnp.zeros((num_slots,), jnp.float32)
            .at[i].add(v).sum(),
            idx, g,
        )
        timed(
            f"scatter_add_f32_sorted_idx{tag}",
            lambda i, v: jnp.zeros((num_slots,), jnp.float32)
            .at[i].add(v).sum(),
            idx_sorted, g,
        )

        def sort_segment(i, v, num_slots=num_slots):
            order = jnp.argsort(i)
            return jax.ops.segment_sum(
                v[order], i[order], num_segments=num_slots
            ).sum()

        timed(f"scatter_sort_segment{tag}", sort_segment, idx, g)
        timed(
            f"gather_lanesum_f32{tag}",
            lambda w, i: w[i].reshape(rows, lanes).sum(axis=1).sum(),
            w32, idx,
        )
        # the exactness-preserving narrow-pull candidate: gather u8
        # codes + u8 zero-mask (2 B/entry vs 4), dequantize per entry
        # after the gather — what SGDConfig's pull filter would run if
        # the narrow gathers win; L1-pruned exact zeros survive via
        # the mask, matching make_pull_lookup's where(w != 0) semantic
        # UNSIGNED codes, like the production quantizer emits
        # (filter/fixing_float.py): affine dequant over 0..255
        qu8 = jax.device_put(
            ((w_np * 10) + 128).clip(0, 255).astype(np.uint8)
        )
        zmask = jax.device_put((w_np != 0).astype(np.uint8))
        timed(
            f"gather_u8_plus_mask_dequant{tag}",
            lambda q, m, i: (
                (q[i].astype(jnp.float32) * 0.1 - 12.8)
                * m[i].astype(jnp.float32)
            ).sum(),
            qu8, zmask, idx,
        )
        # wire-decode formulations: the production tiled unpack
        # (static strided column loads — utils/bitpack.py
        # _unpack_bits_tiled) vs the original two-random-gathers-per
        # -value form it replaced; step_phase_decode measures the
        # integrated phase, this pair isolates the formulation delta
        from parameter_server_tpu.utils import bitpack
        from parameter_server_tpu.utils.bitpack import slot_bits

        bits = slot_bits(num_slots)
        words = jax.device_put(bitpack.stream_to_words(
            bitpack.pack_bits(idx_np, bits), n_idx, bits
        ))
        timed(
            f"unpack_tiled{tag}",
            lambda w, n=n_idx, b=bits: (
                bitpack._unpack_bits_tiled(w, n, b).sum()
            ),
            words,
        )
        timed(
            f"unpack_gather{tag}",
            lambda w, n=n_idx, b=bits: (
                bitpack._unpack_bits_gather(w, n, b).sum()
            ),
            words,
        )
    # dense-FTRL formulation A/B at BIG-table scale (runs once, not
    # per size-loop): the 08-02 attribution session measured the
    # Pallas update kernel at ~295 GB/s effective on a 2^28 table
    # while the XLA dense derive hit ~770 (≈ peak) — if the pure-XLA
    # update matches or beats the kernel at scale, the dense update
    # should flip to XLA above a size threshold the way spmv stayed
    # XLA by measurement. In-process block_rows variants compile
    # ~30-40 s each through the remote-compile helper, so only the
    # seeded default and one large block are swept.
    from parameter_server_tpu.ops.ftrl import ftrl_update, ftrl_update_ref

    # Dense-update formulation crossover, measured HONESTLY: the first
    # A/B round (16:12 captures, single-pass jit without donation) let
    # the Pallas arm pay defensive whole-table copies for its
    # input_output_aliases (the ftrl_update docstring's own warning)
    # and buried small sizes under a ~14.5 ms dispatch floor. An
    # 8-deep in-program chain amortizes both: iteration i+1 consumes
    # iteration i's buffers, so aliasing is free after the first pass
    # and the floor splits 8 ways. Value = ms PER PASS (/8). New
    # metric names — these are a different measurement distribution
    # than the single-pass records and must not pool with them. The
    # pallas arm pins force_pallas (production ftrl_update now
    # auto-flips to XLA at ops.ftrl.xla_min_slots, set from this
    # sweep's verdict).
    n_chain = 8
    sizes_ab = [(1 << 14, "2e14")] if SMOKE else [
        (1 << 25, "2e25"), (1 << 26, "2e26"),
        (1 << 27, "2e27"), (1 << 28, "2e28"),
    ]

    def _chain(update_fn):
        def run(z, n, g):
            def body(_, zn):
                return update_fn(zn[0], zn[1], g)

            z2, n2 = jax.lax.fori_loop(0, n_chain, body, (z, n))
            return z2.sum() + n2.astype(jnp.float32).sum()

        return run

    for S_big, sz in sizes_ab:
        rngb = np.random.default_rng(3)
        zb = jax.device_put(rngb.normal(size=S_big).astype(np.float32))
        nb = jax.device_put((rngb.random(S_big) * 3).astype(np.float32))
        gb = jax.device_put(np.zeros(S_big, np.float32))
        for nm, fn in (
            (f"ftrl_dense_pallas_chain_{sz}",
             _chain(lambda z, n, g: ftrl_update(
                 z, n, g, None, alpha=0.1, beta=1.0, l1=1.0,
                 force_pallas=True))),
            (f"ftrl_dense_xla_chain_{sz}",
             _chain(lambda z, n, g: ftrl_update_ref(
                 z, n, g, None, alpha=0.1, beta=1.0, l1=1.0,
                 l2=0.0))),
        ):
            timed(nm, fn, zb, nb, gb, scale=1.0 / n_chain)
        zb = nb = gb = None
    if skipped_fresh:
        emit({"metric": "gatherx_task_resume", "value": len(skipped_fresh),
              "unit": "variants_skipped_fresh", "skipped": skipped_fresh})
    return 0


def task_scale() -> int:
    """Largest FTRL table one chip holds, with HBM accounting
    (VERDICT r2 item 3; BASELINE north star Criteo-1TB ~800M keys).

    NOTE: the per-size orchestration branch below must run BEFORE any
    jax import/device init — the parent must never hold a live tunnel
    client while a size child (itself a client) runs, and a connected
    parent would keep runtime state alive across sizes, the very
    contamination the per-size split exists to remove."""
    # max_delay=0 rides the donated-step path: ONE live table buffer
    # (input aliased to output) instead of live+snapshot+output, which is
    # what lets 2^29-2^30 (>= the 800M-key north star) fit one chip.
    # 800M is BASELINE.json's Criteo-1TB key count, named directly so the
    # north star is demonstrated even while 2^30 trips the tunnel's
    # remote-compile helper (HTTP 500, 04:04+04:14 captures)
    # (label, num_slots, ftrl_state_dtype): bf16 sqrt_n stores the
    # table at 12 B/slot instead of 16 (z stays f32; logloss tracks
    # f32 within 5e-3 — tests/test_async_sgd.py), lifting the
    # single-chip ceiling another ~1.33x beyond the direct-to-sharded
    # init fix. 2^31 bf16n = 12.9 GB steady state.
    sizes = (
        [("2e16", 1 << 16, "float32"), ("2e17_bf16n", 1 << 17, "bfloat16")]
        if SMOKE
        else [
            ("2e28", 1 << 28, "float32"),
            # same size in bf16n: the direct f32-vs-bf16 state speed
            # comparison (the dense update's HBM traffic drops 16->12
            # B/slot; both run fused Pallas kernels — _kernel vs
            # _kernel_bf16 with its on-core stochastic narrow)
            ("2e28_bf16n", 1 << 28, "bfloat16"),
            ("2e29", 1 << 29, "float32"),
            ("800M", 800_000_000, "float32"),
            ("2e30", 1 << 30, "float32"),
            ("2e31_bf16n", 1 << 31, "bfloat16"),
        ]
    )
    only = os.environ.get("PS_SCALE_ONLY")
    if only is None and not SMOKE:
        # one SUBPROCESS per size: the sizes are run back-to-back and
        # the previous size's table is freed ASYNCHRONOUSLY through
        # the tunnel runtime — 800M's 6 GB still being torn down while
        # 2^30's 8 GB materializes is exactly RESOURCE_EXHAUSTED, and
        # 2^30 alone in a fresh process runs fine (2026-08-02 04:49).
        # A clean client per size makes each capture independent of
        # its predecessors' teardown.
        #
        # The child is a live tunnel client, so it must NEVER be
        # orphaned: a SIGTERM from the watcher (the 2400s task budget
        # can be shorter than a worst-case all-sizes run) converts to
        # SystemExit here so run_graceful's BaseException arm reaps
        # the child gracefully before this parent dies; stdout (the
        # emit-record stream) is forwarded on every path, including
        # timeout (TimeoutExpired.output).
        import signal

        from parameter_server_tpu.utils.subproc import run_graceful

        prev_term = signal.signal(
            signal.SIGTERM, lambda *a: sys.exit(143)
        )
        skipped = []
        try:
            for label, _slots, _dt in sizes:
                if _fresh_capture(f"ftrl_table_{label}"):
                    skipped.append(label)
                    continue
                env = dict(os.environ, PS_SCALE_ONLY=label)
                try:
                    rc, err, out = run_graceful(
                        [sys.executable, os.path.abspath(__file__),
                         "--task", "scale"],
                        timeout_s=900, capture_stdout=True,
                        env=env, cwd=REPO,
                    )
                except subprocess.TimeoutExpired as te:
                    sys.stdout.write(
                        (te.output or b"").decode(errors="replace")
                    )
                    sys.stdout.flush()
                    tail = " | ".join(
                        (te.stderr or b"").decode(errors="replace")
                        .strip().splitlines()[-3:]
                    )
                    emit({"metric": f"ftrl_table_{label}",
                          "error": "size subprocess timeout (900s) — "
                                   f"tunnel wedge mid-size? {tail[:300]}"})
                    continue
                sys.stdout.write(
                    (out or b"").decode(errors="replace")
                )
                sys.stdout.flush()
                if rc != 0:
                    tail = " | ".join(
                        (err or b"").decode(errors="replace")
                        .strip().splitlines()[-3:]
                    )
                    emit({"metric": f"ftrl_table_{label}",
                          "error": f"size subprocess rc={rc}: "
                                   f"{tail[:400]}"})
        finally:
            signal.signal(signal.SIGTERM, prev_term)
        if skipped:
            emit({"metric": "scale_task_resume", "value": len(skipped),
                  "unit": "sizes_skipped_fresh", "skipped": skipped})
        return 0

    import gc

    import jax
    import numpy as np

    from parameter_server_tpu.apps.linear.async_sgd import AsyncSGDWorker
    from parameter_server_tpu.apps.linear.config import (
        Config,
        LearningRateConfig,
        PenaltyConfig,
        SGDConfig,
    )
    from parameter_server_tpu.system.postoffice import Postoffice
    from parameter_server_tpu.utils.sparse import random_sparse

    dev = jax.devices()[0]

    worker = None
    skipped_fresh = []
    for label, num_slots, state_dtype in sizes:
        if only is not None and label != only:
            continue
        if not SMOKE and _fresh_capture(f"ftrl_table_{label}"):
            skipped_fresh.append(label)
            continue  # retry resumption
        try:
            # drop the PREVIOUS size's table before allocating the next:
            # `worker` stays bound across iterations, so without this the
            # old table (up to 8.6 GB) is still alive while the new one
            # materializes — 2^29 + 800M together overflow a 16 GB chip
            # even though each fits alone
            worker = staged = pend = None  # noqa: F841
            gc.collect()
            Postoffice.reset()
            po = Postoffice.instance().start()
            conf = Config()
            conf.penalty = PenaltyConfig(type="l1", lambda_=[1.0])
            conf.learning_rate = LearningRateConfig(
                type="decay", alpha=0.1, beta=1.0
            )
            conf.async_sgd = SGDConfig(
                algo="ftrl", minibatch=16384, num_slots=num_slots,
                max_delay=0, ell_lanes=39, wire="bits",
                ftrl_state_dtype=state_dtype,
            )
            worker = AsyncSGDWorker(conf, mesh=po.mesh)
            raw = [
                random_sparse(16384, 1 << 24, 39, seed=i, binary=True)
                for i in range(4)
            ]
            for b in raw:
                b.y = np.sign(
                    np.random.default_rng(1).random(16384) - 0.5
                ).astype(np.float32)
            worker._padding(raw[0])
            # pre-stage the batches ON DEVICE before the timed loop —
            # the same device-only discipline as the headline bench.
            # The first scale sessions uploaded each 2.2 MB wire batch
            # INSIDE the loop, so step_ms tracked tunnel weather, not
            # the table: three same-code 2^28 sessions drifted 86 →
            # 146 → 206 ms as the link throttled (08-02), while a 2 GB
            # dense FTRL pass is ~10 ms of device work. Batches are
            # read-only to the step (donation applies to the table
            # state), so resubmitting staged trees is sound.
            from parameter_server_tpu.apps.linear.async_sgd import (
                stack_bits_batches,
            )

            # stack the 4 minibatches into ONE scan superbatch (the
            # headline bench's T lever): under per-step dispatch a
            # ~75 ms/submit tunnel-RTT floor hid the table's actual
            # cost — 2^29 timed IDENTICAL to 2^28 (76 vs 75 ms,
            # interactive 08-02 session). _submit_prepped scan-steps
            # a superbatch regardless of SGDConfig.steps_per_launch
            staged = jax.device_put(stack_bits_batches(
                [worker.prep(b, device_put=False) for b in raw]
            ))
            worker.executor.wait(
                worker._submit_prepped(staged, with_aux=False)
            )
            _flush(worker.state)
            n_launch = 3
            t0 = time.perf_counter()
            pend = []
            for i in range(n_launch):
                pend.append(
                    worker._submit_prepped(staged, with_aux=False)
                )
                if len(pend) > 2:
                    worker.executor.wait(pend.pop(0))
            for ts in pend:
                worker.executor.wait(ts)
            _flush(worker.state)
            # divide by the launch's ACTUAL scan depth, not the
            # config knob: _submit_prepped runs staged.steps
            # ministeps regardless of steps_per_launch (only train()
            # consumes the config), so the two could silently diverge
            sec = (time.perf_counter() - t0) / (
                n_launch * staged.steps
            )
            stats = dev.memory_stats() or {}
            bytes_per_slot = 6 if state_dtype == "bfloat16" else 8
            emit(
                {
                    "metric": f"ftrl_table_{label}",
                    "value": round(16384 / sec, 1),
                    "unit": "examples/sec",
                    "num_slots": num_slots,
                    "ftrl_state_dtype": state_dtype,
                    "device_kind": dev.device_kind,
                    "table_gb": round(num_slots * bytes_per_slot / 2**30, 2),
                    "hbm_bytes_in_use": stats.get("bytes_in_use"),
                    "hbm_bytes_limit": stats.get("bytes_limit"),
                    "step_ms": round(sec * 1e3, 2),
                }
            )
        except Exception as e:
            emit({"metric": f"ftrl_table_{label}", "error": repr(e)[:500]})
    if skipped_fresh:
        emit({"metric": "scale_task_resume", "value": len(skipped_fresh),
              "unit": "sizes_skipped_fresh", "skipped": skipped_fresh})
    return 0


INTERNAL = {"link": task_link, "flash": task_flash, "lm": task_lm,
            "scale": task_scale, "serve": task_serve,
            "spec_big": task_spec_big, "gatherx": task_gatherx}


# ---------------------------------------------------------------------------
# watcher (parent side: probes, spawns tasks, appends the log)
# ---------------------------------------------------------------------------


def _load_state() -> dict:
    try:
        with open(STATE) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def _save_state(st: dict) -> None:
    os.makedirs(os.path.dirname(STATE), exist_ok=True)
    with open(STATE + ".tmp", "w") as f:
        json.dump(st, f, indent=1)
    os.replace(STATE + ".tmp", STATE)


def _append_log(lines) -> None:
    new = not os.path.exists(LOG_MD)
    with open(LOG_MD, "a") as f:
        if new:
            f.write(
                "# On-chip benchmark log\n\n"
                "Append-only record written by `script/onchip.py` the "
                "moment the tunneled TPU becomes reachable. Every entry "
                "is a timestamped JSON line as produced on the chip.\n\n"
            )
        for ln in lines:
            f.write(ln.rstrip() + "\n")


def _wlog(msg: str) -> None:
    line = f"[{_now()}] {msg}"
    print(line, flush=True)
    os.makedirs(os.path.dirname(WATCH_LOG), exist_ok=True)
    with open(WATCH_LOG, "a") as f:
        f.write(line + "\n")


def probe(timeout_s: float = 150.0) -> "tuple[bool, str]":
    """(ok, diagnosis). A nonzero exit is a deterministic CRASH (bad
    install/env — retrying won't help, surface the stderr tail); a
    timeout is the tunnel wedge (transient, keep retrying)."""
    from parameter_server_tpu.utils.device_lock import (
        device_lock,
        foreign_priority,
        held_env,
    )

    req = foreign_priority()
    if req:
        # a driver/interactive bench announced it needs the device —
        # don't even probe (two concurrent tunnel clients wedge each
        # other); stay away while the request is fresh
        return False, f"yielding to priority request ({req})"
    with device_lock(timeout_s=0) as got:
        if not got and got.reason == "busy":
            # another process (a driver/interactive bench) is on the
            # device — that is not a wedge, just not our turn
            return False, "device busy (another process holds the lock)"
        # "unsupported": no exclusion exists to wait for — probe anyway
        from parameter_server_tpu.utils.subproc import (
            PROBE_CHILD_SRC,
            run_graceful,
        )

        try:
            rc, err, _ = run_graceful(
                [sys.executable, "-c", PROBE_CHILD_SRC], timeout_s,
                cwd=REPO, env=held_env(),
            )
            if rc == 0:
                return True, "ok"
            tail = " | ".join(
                err.decode(errors="replace").strip().splitlines()[-3:]
            )
            return False, f"device init CRASHED (not a wedge): {tail}"
        except subprocess.TimeoutExpired:
            return False, f"device init hang >{timeout_s:.0f}s (tunnel wedge?)"


def run_task(name: str, argv, timeout_s: int) -> "bool | None":
    """True = ok, False = failed, None = deferred (device busy or
    preempted by a priority request — does not consume an attempt; a
    live bench may hold the device for hours, and the watcher's job is
    to wait its turn, never collide).

    While the task child runs, a foreign priority request (the round
    driver's bench announcing itself — see utils/device_lock.py)
    PREEMPTS it: the child is killed, its partial JSON is appended with
    a preempted marker, and the flock is released within ~2s so the
    requester never waits out a 5400s task hold."""
    from parameter_server_tpu.utils.device_lock import (
        device_lock,
        foreign_priority,
        held_env,
    )

    if argv is None:
        argv = [sys.executable, os.path.abspath(__file__), "--task", name]
    elif SMOKE:
        argv = argv + ["--smoke"]
    req = foreign_priority()
    if req:
        _wlog(f"task {name}: deferred (yielding to priority request {req})")
        return None
    # hold the device flock for the child's whole run so a driver
    # bench starting mid-task waits instead of colliding; the child
    # sees PS_DEVICE_LOCK_HELD and does not re-acquire
    wait0 = time.perf_counter()
    with device_lock(timeout_s=600) as lock:
        if not lock and lock.reason == "busy":
            _wlog(f"task {name}: deferred (device busy after "
                  f"{time.perf_counter() - wait0:.0f}s wait)")
            return None
        waited = time.perf_counter() - wait0
        if waited > 10:
            _wlog(f"task {name}: lock acquired after {waited:.0f}s wait")
        _wlog(f"task {name}: starting ({' '.join(argv)})")
        t0 = time.perf_counter()
        preempted = None
        timed_out = False

        def _stop(p):
            # SIGTERM + grace before SIGKILL: the child is a live
            # tunnel client, and a SIGKILLed client has left the
            # relay's claim/grant protocol stuck for hours (bench.py
            # probe_device docstring) — a graceful exit lets it
            # release its claim, which is the whole point of handing
            # the device over quickly
            p.terminate()
            try:
                p.wait(timeout=10.0)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()

        with tempfile.TemporaryFile(mode="w+") as fout, \
                tempfile.TemporaryFile(mode="w+") as ferr:
            p = subprocess.Popen(
                argv, stdout=fout, stderr=ferr, text=True,
                cwd=REPO, env=held_env(),
            )
            rc = None
            while True:
                try:
                    rc = p.wait(timeout=2.0)
                    break
                except subprocess.TimeoutExpired:
                    pass
                if time.perf_counter() - t0 > timeout_s:
                    timed_out = True
                    _stop(p)
                    rc = p.returncode
                    break
                req = foreign_priority(ignore_pid=p.pid)
                if req:
                    preempted = req
                    _stop(p)
                    rc = p.returncode
                    break
            fout.seek(0)
            out = fout.read()
            ferr.seek(0)
            err_tail = "\n".join(ferr.read().strip().splitlines()[-4:])
        if timed_out:
            err_tail = f"TIMEOUT after {timeout_s}s"
        dt = time.perf_counter() - t0
    if preempted:
        _wlog(f"task {name}: PREEMPTED after {dt:.0f}s "
              f"(priority request {preempted}); lock released")
    lines = [f"\n## {_now()} — {name} (rc={rc}, {dt:.0f}s"
             + (", preempted by priority request" if preempted else "")
             + ")", "```"]
    json_lines = [
        ln for ln in out.splitlines() if ln.startswith("{")
    ]
    lines += json_lines or ["(no JSON output)"]
    if rc != 0 and not preempted and err_tail:
        lines += [f"stderr: {err_tail}"]
    lines += ["```"]
    _append_log(lines)
    if preempted:
        return None  # not an attempt; retried after the requester's turn
    ok = rc == 0 and bool(json_lines)
    _wlog(f"task {name}: {'ok' if ok else 'FAILED'} in {dt:.0f}s")
    return ok


def _state_stale(rec, max_age_s: float = 86400.0) -> bool:
    """A task-state entry older than ``max_age_s`` (or unparseable) no
    longer gates scheduling — same freshness horizon as
    ``_fresh_capture``."""
    if not isinstance(rec, dict):
        return True
    try:
        t = time.mktime(
            time.strptime(rec.get("last_start", ""), "%Y-%m-%d %H:%M:%S")
        )
    except (TypeError, ValueError, OverflowError):
        # TypeError: a null/numeric last_start from a hand-edited or
        # repaired state file must read as stale, not kill the watcher
        return True
    return time.time() - t > max_age_s


def watch(args) -> int:
    _wlog(
        f"watcher started (interval {args.interval}s, "
        f"max {args.max_attempts} attempts/task)"
    )
    last_refresh = time.time()
    last_diag = None
    while True:
        up, diag = probe(args.probe_timeout)
        if not up:
            if diag != last_diag:  # don't spam identical lines for hours
                _wlog(f"probe: {diag}")
                last_diag = diag
            time.sleep(args.interval)
            continue
        last_diag = None
        _wlog("probe: device UP")
        # re-read state every cycle: a concurrent `make bench-all` may
        # have completed tasks since the last iteration. Entries older
        # than a day are treated as ABSENT: a stale "ok" from a prior
        # session must not starve fresh captures at the next window
        # (observed: link ok from 08-01 would have been skipped on
        # 08-02), and a task that burned its attempt budget against
        # yesterday's wedge deserves a fresh budget today.
        st = {
            n: rec for n, rec in _load_state().items()
            if not _state_stale(rec)
        }
        pending = [
            (n, a, t)
            for n, a, t in TASKS
            if st.get(n, {}).get("status") != "ok"
            and st.get(n, {}).get("attempts", 0) < args.max_attempts
        ]
        if not pending:
            # all green: refresh the bandwidth-sensitive numbers every
            # few hours to catch the link at different speeds
            if time.time() - last_refresh > args.refresh_s:
                for n in ("link", "bench"):
                    argv, to = next(
                        (a, t) for nn, a, t in TASKS if nn == n
                    )
                    run_task(n, argv, to)
                last_refresh = time.time()
            time.sleep(args.interval)
            continue
        for name, argv, to in pending:
            st = _load_state()  # freshest view before mutating
            rec = st.setdefault(name, {"attempts": 0})
            if _state_stale(rec):
                # prior-session attempts aged out of scheduling above;
                # age them out of the BUDGET too, or a task that burned
                # its budget yesterday gets exactly one retry today.
                # Drop the status as well: last_start is refreshed
                # below, and a deferred/preempted re-run would
                # otherwise leave a RE-FRESHENED 'ok' that skips the
                # task for another 24h without it ever running
                rec["attempts"] = 0
                rec.pop("status", None)
            rec["attempts"] += 1
            rec["last_start"] = _now()
            _save_state(st)
            ok = run_task(name, argv, to)
            st = _load_state()
            st.setdefault(name, {"attempts": rec["attempts"]})
            if ok is None:
                # deferred: device busy — not an attempt against this
                # task; back off and let the holder finish
                st[name]["attempts"] = rec["attempts"] - 1
                _save_state(st)
                break
            st[name]["status"] = "ok" if ok else "fail"
            _save_state(st)
            if not ok and not probe(args.probe_timeout)[0]:
                _wlog("device went away mid-suite; back to probing")
                break
        last_refresh = time.time()
        time.sleep(args.interval)
    return 0


def main() -> int:
    # the watcher preempts task children with SIGTERM (grace before
    # SIGKILL); default disposition would terminate without running
    # Python finalizers — convert to SystemExit so the tunnel client
    # gets its atexit/GC shot at releasing the device claim
    import signal

    with contextlib.suppress(ValueError):  # non-main thread: leave it
        signal.signal(signal.SIGTERM, lambda *_: sys.exit(143))
    ap = argparse.ArgumentParser()
    ap.add_argument("--task", choices=sorted(INTERNAL))
    ap.add_argument("--watch", action="store_true")
    ap.add_argument("--once", action="store_true",
                    help="probe once; if up run all pending tasks, then exit")
    ap.add_argument("--interval", type=float, default=120.0)
    ap.add_argument("--probe-timeout", type=float, default=150.0)
    ap.add_argument("--max-attempts", type=int, default=5)
    ap.add_argument("--refresh-s", type=float, default=7200.0)
    args = ap.parse_args()
    if args.task:
        if os.environ.get("JAX_PLATFORMS"):
            import jax

            jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
        # persistent compile cache for tasks that never build a
        # Postoffice (link, flash — the Mosaic kernels recompile ~27s
        # per attempt otherwise); Postoffice.start() covers the rest
        from parameter_server_tpu.utils.compile_cache import enable

        enable()
        return INTERNAL[args.task]()
    if args.once:
        up, diag = probe(args.probe_timeout)
        if not up:
            print(f"device unreachable: {diag}", file=sys.stderr)
            return 1
        st = _load_state()
        rc = 0
        for name, argv, to in TASKS:
            if _state_stale(st.get(name, {})):
                # same staleness semantics as watch(): a day-old 'ok'
                # must not skip the task, and yesterday's burned
                # attempt budget resets
                st[name] = {"attempts": 0}
            if st.get(name, {}).get("status") == "ok":
                continue
            ok = run_task(name, argv, to)
            if ok is None:  # device busy: not an attempt, stop the pass
                print(f"{name}: deferred (device busy)", file=sys.stderr)
                rc |= 1
                break
            st.setdefault(name, {"attempts": 0})
            st[name]["attempts"] = st[name].get("attempts", 0) + 1
            st[name]["status"] = "ok" if ok else "fail"
            # last_start: without it the watcher's staleness filter
            # treats this entry as aged-out and re-runs a task a
            # concurrent bench-all just finished
            st[name]["last_start"] = _now()
            _save_state(st)
            rc |= 0 if ok else 1
        return rc
    if args.watch:
        return watch(args)
    ap.error("one of --task/--watch/--once required")


if __name__ == "__main__":
    sys.exit(main())
