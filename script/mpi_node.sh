#!/usr/bin/env bash
# Per-rank adapter for cluster launchers (mpirun/srun): translate the
# launcher's rank/size environment into the framework's multi-host env
# contract (PS_PROCESS_ID / PS_NUM_PROCESSES / PS_COORDINATOR_ADDRESS,
# consumed by parallel/distributed.init_distributed) and exec the
# training command.
#
# TPU-native counterpart of the reference's script/mpi_node.sh, which
# maps PMI_RANK/OMPI_COMM_WORLD_RANK onto scheduler/server/worker
# process roles. Here there is ONE SPMD program per host — roles are
# mesh axes — so the only thing rank decides is the process id, and
# process 0 doubles as the coordinator (the reference's scheduler).
#
# Usage (normally via mpi_root.sh):
#   mpi_node.sh <coordinator_host:port> <command...>
#
# Rank sources, in order: OpenMPI, MPICH/PMI, Slurm, PS_PROCESS_ID
# already set by a custom launcher.
set -euo pipefail
if (( $# < 2 )); then
  echo "usage: mpi_node.sh <coordinator_host:port> <command...>" >&2
  exit 2
fi
COORD=$1; shift

if [[ -n ${OMPI_COMM_WORLD_RANK:-} ]]; then
  rank=${OMPI_COMM_WORLD_RANK}; size=${OMPI_COMM_WORLD_SIZE}
elif [[ -n ${PMI_RANK:-} ]]; then
  rank=${PMI_RANK}; size=${PMI_SIZE}
elif [[ -n ${SLURM_PROCID:-} ]]; then
  rank=${SLURM_PROCID}; size=${SLURM_NTASKS}
elif [[ -n ${PS_PROCESS_ID:-} && -n ${PS_NUM_PROCESSES:-} ]]; then
  rank=${PS_PROCESS_ID}; size=${PS_NUM_PROCESSES}
else
  echo "mpi_node.sh: no rank found (OMPI_COMM_WORLD_RANK / PMI_RANK / \
SLURM_PROCID / PS_PROCESS_ID all unset)" >&2
  exit 1
fi

ROOT=$(cd "$(dirname "$0")/.." && pwd)
export PYTHONPATH="${ROOT}${PYTHONPATH:+:$PYTHONPATH}"
export PS_COORDINATOR_ADDRESS="${COORD}"
export PS_NUM_PROCESSES="${size}"
export PS_PROCESS_ID="${rank}"
exec "$@"
