#!/usr/bin/env python
"""Summarize BENCH_ONCHIP.md into one table per metric.

Reads the append-only evidence log and prints, for every metric: the
latest successful on-chip value, the cross-session median/spread (the
number PERFORMANCE.md should quote — r3 verdict weak #8), capture
count, the newest capture's timestamp, and any trailing error. Smoke
(cpu) records are listed separately so they can never be mistaken for
chip evidence.

Usage: python script/summarize_evidence.py [--all] [--since HOURS]
  --all          also list cpu-only (smoke) metric names
  --since HOURS  only consider records newer than HOURS (default: all)

Metrics whose newest record is an error always print (a stale success
followed by fresh wedges is exactly the case to surface).
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _onchip():
    spec = importlib.util.spec_from_file_location(
        "onchip_log", os.path.join(REPO, "script", "onchip.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--all", action="store_true")
    ap.add_argument("--since", type=float, default=None, metavar="HOURS")
    args = ap.parse_args()

    onchip = _onchip()
    cutoff = (
        time.time() - args.since * 3600.0
        if args.since is not None
        else 0.0
    )
    chip: dict = {}
    errors: dict = {}
    cpu_only: set = set()
    for ts, d in onchip._iter_log_records(onchip.LOG_MD):
        if ts < cutoff:
            continue
        m = d.get("metric")
        if not m:
            continue
        if "error" in d:
            errors[m] = (ts, str(d["error"])[:120])
            continue
        # the ONE shared definition of chip evidence (onchip._chip_success):
        # excludes cpu/smoke records, value<=0, and diff_noisy deflated
        # numbers — the same filters session_stats/_fresh_capture apply,
        # so this table can never disagree with the log's own medians
        if not onchip._chip_success(d):
            if d.get("device_kind") in (None, "cpu"):
                cpu_only.add(m)
            continue
        chip.setdefault(m, []).append(
            (ts, float(d["value"]), d.get("unit", ""))
        )

    def fmt_ts(ts):
        return time.strftime("%m-%d %H:%M", time.localtime(ts)) if ts else "?"

    rows = []
    for m, caps in sorted(chip.items()):
        caps.sort()
        vals = sorted(v for _, v, _ in caps)
        med = vals[len(vals) // 2]
        spread = (vals[-1] - vals[0]) / med if med else 0.0
        ts, latest, unit = caps[-1]
        rows.append(
            (m, latest, med, len(caps), round(spread, 2), unit, fmt_ts(ts))
        )
    if rows:
        wm = max(len(r[0]) for r in rows)
        print(f"{'metric':<{wm}}  {'latest':>12}  {'median':>12}  "
              f"n  sprd  unit            newest")
        for m, latest, med, n, spread, unit, ts in rows:
            print(f"{m:<{wm}}  {latest:>12,.1f}  {med:>12,.1f}  "
                  f"{n}  {spread:<4}  {unit:<14}  {ts}")
    else:
        print("(no successful on-chip captures in range)")

    # errors newer than the metric's latest success are live failures
    # (an old success + fresh wedges is exactly the case to surface);
    # metrics with ONLY errors always print
    live_err = {}
    for m, (ts, e) in errors.items():
        latest_ok = max((t for t, _, _ in chip.get(m, [])), default=None)
        if latest_ok is None or ts > latest_ok:
            live_err[m] = (ts, e, latest_ok is not None)
    if live_err:
        print("\nmetrics whose NEWEST record is an error:")
        for m, (ts, e, had_ok) in sorted(live_err.items()):
            note = " (stale success above)" if had_ok else ""
            print(f"  {m}  [{fmt_ts(ts)}]{note}  {e}")
    if cpu_only - set(chip) and args.all:
        print("\ncpu-only (smoke) metrics:", ", ".join(sorted(cpu_only - set(chip))))
    return 0


if __name__ == "__main__":
    sys.exit(main())
