#!/usr/bin/env bash
# Idempotently ensure the on-chip evidence watcher is running.
# Safe to call from any shell hook or session bootstrap: exits 0
# without action when a watcher is already alive. The watcher itself
# serializes against benches via the device flock + priority protocol
# (utils/device_lock.py), so starting it can never collide with a
# running capture.
set -euo pipefail
DIR=$(cd "$(dirname "$0")/.." && pwd)
if pgrep -f "onchip.py --watch" >/dev/null 2>&1; then
  echo "watcher already running (pid $(pgrep -f 'onchip.py --watch' | head -1))"
  exit 0
fi
cd "$DIR"
nohup python script/onchip.py --watch >> doc/onchip_watch_stdout.log 2>&1 &
disown
echo "watcher started (pid $!)"
