#!/usr/bin/env bash
# Cluster launcher: start an N-process parameter_server_tpu job through
# mpirun (OpenMPI/MPICH) with mpi_node.sh adapting each rank into the
# framework's env contract. TPU-native counterpart of the reference's
# script/mpi_root.sh (which computed the scheduler node and mpirun'd
# mpi_node.sh across a hostfile).
#
# Usage:
#   script/mpi_root.sh <N> <command...>
# e.g.
#   script/mpi_root.sh 4 python -m parameter_server_tpu.apps.lm.main \
#       --steps 100 --fsdp
#
# Env knobs:
#   PS_HOSTFILE  passed to mpirun -hostfile (multi-machine runs); the
#                FIRST host in it must be reachable from every rank —
#                it becomes the jax.distributed coordinator
#   PS_PORT      coordinator port (default: 29431)
#   PS_MPIRUN    mpirun binary (default: mpirun from PATH)
#
# Without any MPI runtime on PATH the launcher falls back to N local
# processes with emulated ranks — same code path through mpi_node.sh,
# so CI exercises the launcher without an MPI install. Local fallback
# and single-host mpirun both force a CPU device mesh per process
# (PS_LOCAL_DEVICES, default 2), mirroring local.sh; on a real pod the
# TPU plugin provides devices and JAX_PLATFORMS is left alone.
set -euo pipefail
N=${1:?usage: mpi_root.sh <N> <command...>}; shift
PORT=${PS_PORT:-29431}
MPIRUN=${PS_MPIRUN:-mpirun}
DIR=$(cd "$(dirname "$0")" && pwd)

if command -v "${MPIRUN}" >/dev/null 2>&1; then
  if [[ -n ${PS_HOSTFILE:-} ]]; then
    # multi-machine: leave the device platform alone (a real pod's TPU
    # plugin provides devices); first host doubles as coordinator
    host=$(awk 'NF && $1 !~ /^#/ {print $1; exit}' "${PS_HOSTFILE}")
    exec "${MPIRUN}" -hostfile "${PS_HOSTFILE}" -np "${N}" \
      "${DIR}/mpi_node.sh" "${host}:${PORT}" "$@"
  fi
  # single-host mpirun (dev box): ranks need the same CPU-mesh env the
  # local fallback and local.sh force, or every rank grabs the same
  # default platform/device and the mesh is wrong; `env` rides inside
  # the command so it works for OpenMPI and MPICH alike
  exec "${MPIRUN}" -np "${N}" \
    env -u PALLAS_AXON_POOL_IPS \
    JAX_PLATFORMS=cpu \
    XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=${PS_LOCAL_DEVICES:-2}" \
    "${DIR}/mpi_node.sh" "127.0.0.1:${PORT}" "$@"
fi

# ---- no MPI runtime: local emulation through the same adapter ----
echo "mpi_root.sh: ${MPIRUN} not found; emulating ${N} local ranks" >&2
DEVS=${PS_LOCAL_DEVICES:-2}
pids=()
cleanup() { kill "${pids[@]}" 2>/dev/null || true; }
trap cleanup INT TERM
for ((i = N - 1; i >= 0; i--)); do
  env -u PALLAS_AXON_POOL_IPS \
    JAX_PLATFORMS=cpu \
    XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=${DEVS}" \
    PS_PROCESS_ID="$i" PS_NUM_PROCESSES="$N" \
    "${DIR}/mpi_node.sh" "127.0.0.1:${PORT}" "$@" &
  pids+=($!)
done
# fail fast, and disambiguate "no children left" from a child that
# itself exited 127 (command not found): wait -n -p reports WHICH pid
# was reaped; 127 with no reaped pid means the set is drained
rc=0
remaining=${#pids[@]}
while (( remaining > 0 )); do
  r=0
  reaped=""
  wait -n -p reaped "${pids[@]}" 2>/dev/null || r=$?
  if [[ -z ${reaped} ]]; then break; fi  # set drained
  remaining=$((remaining - 1))
  if (( r != 0 )); then
    if (( rc == 0 )); then rc=$r; fi   # first failure wins, not SIGTERMs
    cleanup
  fi
done
exit "$rc"
