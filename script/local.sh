#!/usr/bin/env bash
# Launch an N-process parameter_server_tpu job on ONE machine.
#
# TPU-native counterpart of the reference's script/local.sh (which starts
# a scheduler + S servers + W workers as local processes): here every
# process is a "host" joined via jax.distributed (process 0 doubles as the
# coordinator, the reference's scheduler), and server/worker roles are
# mesh AXES inside the SPMD program, not separate processes.
#
# Usage:
#   script/local.sh <num_hosts> <command...>
# e.g.
#   script/local.sh 2 python -m parameter_server_tpu.apps.linear.main \
#       conf.conf --num-servers 2
#
# Env knobs:
#   PS_LOCAL_DEVICES  virtual CPU devices per process (default 2)
#   PS_PORT           coordinator port (default: random free-ish)
#
# On a real multi-host TPU pod, run the same command on every host with
# PS_COORDINATOR_ADDRESS=<host0>:<port> PS_NUM_PROCESSES=<N>
# PS_PROCESS_ID=<i> set by your cluster launcher (srun/mpirun/k8s), and
# leave JAX_PLATFORMS alone so the TPU plugin provides the devices.
set -euo pipefail
N=${1:?usage: local.sh <num_hosts> <command...>}; shift
PORT=${PS_PORT:-$(( (RANDOM % 20000) + 20000 ))}
DEVS=${PS_LOCAL_DEVICES:-2}
ROOT=$(cd "$(dirname "$0")/.." && pwd)
export PYTHONPATH="${ROOT}${PYTHONPATH:+:$PYTHONPATH}"

pids=()
cleanup() { kill "${pids[@]}" 2>/dev/null || true; }
trap cleanup INT TERM

for ((i = N - 1; i >= 0; i--)); do
  env -u PALLAS_AXON_POOL_IPS \
    JAX_PLATFORMS=cpu \
    XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=${DEVS}" \
    PS_COORDINATOR_ADDRESS="127.0.0.1:${PORT}" \
    PS_NUM_PROCESSES="$N" \
    PS_PROCESS_ID="$i" \
    "$@" &
  pids+=($!)
done

# fail fast: if any child exits nonzero, kill the siblings instead of
# letting them block in the rendezvous until the coordinator timeout.
# wait -n -p disambiguates "no children left" from a child that itself
# exited 127 (command not found): 127 with no reaped pid = drained.
rc=0
remaining=${#pids[@]}
while (( remaining > 0 )); do
  r=0
  reaped=""
  wait -n -p reaped "${pids[@]}" 2>/dev/null || r=$?
  if [[ -z ${reaped} ]]; then break; fi  # set drained
  remaining=$((remaining - 1))
  if (( r != 0 )); then
    if (( rc == 0 )); then rc=$r; fi   # keep the FIRST failure, not SIGTERMs
    cleanup
  fi
done
exit "$rc"
