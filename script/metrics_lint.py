#!/usr/bin/env python
"""metrics-lint: validate the telemetry metric catalog (fast, CPU-only).

Instantiates every instrument family from
``parameter_server_tpu.telemetry.instruments`` against a fresh registry
and fails on:

- duplicate metric names, or one name re-declared with a different
  kind/labels/buckets across families (the registry raises);
- non-snake_case metric or label names (the registry raises);
- counters missing the ``_total`` suffix / histograms missing a
  ``_seconds`` or ``_bytes`` unit suffix (naming-convention drift);
- a render_text() exposition that does not parse as Prometheus text.

Runs as the ``metrics`` pass of the pslint static-analysis suite
(``make pslint``, doc/STATIC_ANALYSIS.md) — the logic lives here as the
single source of truth and pslint wraps it. ``make metrics-lint``
aliases the single-pass pslint run; this file also stays directly
runnable and is exercised as a tier-1 test in tests/test_telemetry.py
so catalog drift fails CI before it ships.
"""

from __future__ import annotations

import re
import sys

EXPOSITION_LINE = re.compile(
    r"^[a-z_][a-z0-9_]*(\{[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"]*\""
    r"(,[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"]*\")*\})? [^ ]+$"
)


def lint(root: "str | None" = None) -> list:
    """Returns a list of problem strings (empty = clean).

    ``root`` selects which checkout's ``parameter_server_tpu`` to
    validate (pslint passes its ``--root`` through); default is this
    script's own repo. Caveat: Python's module cache wins — in a
    process that already imported the package (pytest), the cached
    import is what gets validated regardless of ``root``; the pslint
    CLI runs fresh, where ``root`` is honored."""
    import os

    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, root)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from parameter_server_tpu.telemetry.instruments import install_all
    from parameter_server_tpu.telemetry.registry import MetricsRegistry

    problems = []
    reg = MetricsRegistry()
    try:
        instruments = install_all(reg)  # raises on dup / bad names
        install_all(reg)  # second pass must be idempotent
    except Exception as e:
        return [f"catalog failed to install: {type(e).__name__}: {e}"]

    for name, inst in sorted(instruments.items()):
        if inst.kind == "counter" and not name.endswith("_total"):
            problems.append(f"counter {name!r} should end in '_total'")
        if inst.kind == "histogram" and not (
            name.endswith("_seconds") or name.endswith("_bytes")
        ):
            problems.append(
                f"histogram {name!r} should carry a unit suffix "
                "('_seconds' or '_bytes')"
            )

    # exposition must parse even with every series present: record one
    # sample per instrument (labeled instruments get a probe label set)
    for inst in instruments.values():
        target = (
            inst.labels(**{ln: "probe" for ln in inst.labelnames})
            if inst.labelnames
            else inst
        )
        if inst.kind == "histogram":
            target.observe(0.001)
        elif inst.kind == "gauge":
            target.set(1.0)
        else:
            target.inc()
    for line in reg.render_text().splitlines():
        if not line or line.startswith("#"):
            continue
        if not EXPOSITION_LINE.match(line):
            problems.append(f"unparseable exposition line: {line!r}")
    return problems


def main() -> int:
    problems = lint()
    if problems:
        for p in problems:
            print(f"metrics-lint: {p}", file=sys.stderr)
        print(f"metrics-lint: FAILED ({len(problems)} problems)", file=sys.stderr)
        return 1
    print("metrics-lint: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
