#!/usr/bin/env python
"""metrics-lint: validate the telemetry metric catalog (fast, CPU-only).

Instantiates every instrument family from
``parameter_server_tpu.telemetry.instruments`` against a fresh registry
and fails on:

- duplicate metric names, or one name re-declared with a different
  kind/labels/buckets across families (the registry raises);
- non-snake_case metric or label names (the registry raises);
- counters missing the ``_total`` suffix / histograms missing a
  ``_seconds`` or ``_bytes`` unit suffix (naming-convention drift);
- a render_text() exposition that does not parse as Prometheus text;
- **orphan registrations**: any ``ps_*`` instrument registered by name
  anywhere in the package (or bench.py) outside the canonical catalog.
  The exposition endpoint serves whatever the registry holds, so a
  call-site-invented name would ship undocumented, un-linted series —
  every ``ps_*`` name must exist in ``instruments.py`` (satellite of
  the cluster-metrics-plane PR; static AST scan, no imports).

Runs as the ``metrics`` pass of the pslint static-analysis suite
(``make pslint``, doc/STATIC_ANALYSIS.md) — the logic lives here as the
single source of truth and pslint wraps it. ``make metrics-lint``
aliases the single-pass pslint run; this file also stays directly
runnable and is exercised as a tier-1 test in tests/test_telemetry.py
so catalog drift fails CI before it ships.
"""

from __future__ import annotations

import ast
import os
import re
import sys

EXPOSITION_LINE = re.compile(
    r"^[a-z_][a-z0-9_]*(\{[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"]*\""
    r"(,[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"]*\")*\})? [^ ]+$"
)

#: registry methods whose first positional arg is a metric name
_REGISTER_METHODS = frozenset({
    "counter", "gauge", "histogram",
    "ensure_counter", "ensure_gauge", "ensure_histogram",
})

#: the one module allowed to declare ps_* names (the canonical catalog)
_CATALOG_REL = os.path.join("telemetry", "instruments.py")


def orphan_problems(root: str, catalog_names: "set[str]") -> list:
    """Static AST sweep: every ``reg.counter("ps_...")``-shaped call in
    the package (+ bench.py) must name a metric the canonical catalog
    declares. Catches runtime-registered orphans that would be served
    by the exposition endpoint but documented and linted nowhere."""
    problems = []
    pkg = os.path.join(root, "parameter_server_tpu")
    paths = [os.path.join(root, "bench.py")]
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        paths.extend(
            os.path.join(dirpath, f) for f in filenames if f.endswith(".py")
        )
    for path in sorted(paths):
        rel = os.path.relpath(path, root)
        if rel.endswith(_CATALOG_REL) or not os.path.exists(path):
            continue
        try:
            with open(path, encoding="utf-8") as f:
                tree = ast.parse(f.read(), filename=rel)
        except SyntaxError as e:
            problems.append(f"{rel}: unparseable for orphan scan: {e}")
            continue
        for node in ast.walk(tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _REGISTER_METHODS
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                continue
            name = node.args[0].value
            if name.startswith("ps_") and name not in catalog_names:
                problems.append(
                    f"{rel}:{node.lineno} registers ps_* metric "
                    f"{name!r} outside the instruments.py catalog "
                    "(orphan: served but undocumented/unlinted)"
                )
    return problems


def lint(root: "str | None" = None) -> list:
    """Returns a list of problem strings (empty = clean).

    ``root`` selects which checkout's ``parameter_server_tpu`` to
    validate (pslint passes its ``--root`` through); default is this
    script's own repo. Caveat: Python's module cache wins — in a
    process that already imported the package (pytest), the cached
    import is what gets validated regardless of ``root``; the pslint
    CLI runs fresh, where ``root`` is honored. The orphan scan is
    static (AST over ``root``) and honors ``root`` either way."""
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, root)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from parameter_server_tpu.telemetry.instruments import install_all
    from parameter_server_tpu.telemetry.registry import MetricsRegistry

    problems = []
    reg = MetricsRegistry()
    try:
        instruments = install_all(reg)  # raises on dup / bad names
        install_all(reg)  # second pass must be idempotent
    except Exception as e:
        return [f"catalog failed to install: {type(e).__name__}: {e}"]

    for name, inst in sorted(instruments.items()):
        if inst.kind == "counter" and not name.endswith("_total"):
            problems.append(f"counter {name!r} should end in '_total'")
        # histograms carry their unit in the name; ministeps is the
        # learning plane's staleness unit (a logical count, like the
        # Prometheus convention's base units — never an alias for time)
        if inst.kind == "histogram" and not name.endswith(
            ("_seconds", "_bytes", "_ministeps")
        ):
            problems.append(
                f"histogram {name!r} should carry a unit suffix "
                "('_seconds', '_bytes' or '_ministeps')"
            )

    # exposition must parse even with every series present: record one
    # sample per instrument (labeled instruments get a probe label set)
    for inst in instruments.values():
        target = (
            inst.labels(**{ln: "probe" for ln in inst.labelnames})
            if inst.labelnames
            else inst
        )
        if inst.kind == "histogram":
            target.observe(0.001)
        elif inst.kind == "gauge":
            target.set(1.0)
        else:
            target.inc()
    for line in reg.render_text().splitlines():
        if not line or line.startswith("#"):
            continue
        if not EXPOSITION_LINE.match(line):
            problems.append(f"unparseable exposition line: {line!r}")

    problems.extend(orphan_problems(root, set(instruments)))
    return problems


def main() -> int:
    problems = lint()
    if problems:
        for p in problems:
            print(f"metrics-lint: {p}", file=sys.stderr)
        print(f"metrics-lint: FAILED ({len(problems)} problems)", file=sys.stderr)
        return 1
    print("metrics-lint: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
