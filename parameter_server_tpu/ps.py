"""Simple top-level interface for writing PS programs.

Counterpart of ``src/ps.h`` (reference: ps.h:1-80): the convenience façade a
user program imports to query its node identity (``my_node_id``, ``is_worker``,
``my_rank``...), build apps, and boot/stop the system. The reference runs one
OS process per node and reads the role from flags; the TPU-native runtime is
a single SPMD process that drives every role over the device mesh, so
``run_system`` plays the part of ``script/local.sh`` + ``RunSystem``: it
instantiates the scheduler/server/worker apps from one factory and executes
worker ``run()`` bodies (concurrently, like separate node processes), with a
per-thread *current node* so the ps.h-style role helpers answer correctly
inside each app body.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Callable, List, Optional

from .system.customer import App
from .system.executor import NodeGroups
from .system.manager import Node
from .system.message import Message, Task
from .system.postoffice import Postoffice
from .telemetry import spans as telemetry_spans
from .utils.range import Range

__all__ = [
    "App",
    "NodeGroups",
    "start_system",
    "stop_system",
    "run_system",
    "submit",
    "my_app",
    "my_node",
    "my_node_id",
    "is_worker",
    "is_server",
    "is_scheduler",
    "my_key_range",
    "scheduler_id",
    "next_customer_id",
    "my_rank",
    "rank_size",
    "wait_servers_ready",
    "wait_workers_ready",
]

_tls = threading.local()


def _current_node() -> Node:
    node = getattr(_tls, "node", None)
    if node is None:
        # Outside run_system the driving process acts as the scheduler,
        # matching the reference where the root process is node "H".
        nodes = Postoffice.instance().manager.nodes
        return nodes[0] if nodes else Node(Node.SCHEDULER, 0)
    return node


def _set_current_node(node: Optional[Node]) -> None:
    _tls.node = node


# -- system lifecycle (ref ps.h StartSystem/StopSystem/RunSystem) --


def start_system(
    num_workers: Optional[int] = None,
    num_servers: int = 1,
    key_space: Optional[Range] = None,
) -> Postoffice:
    """Boot the postoffice: build the device mesh and the node table."""
    return Postoffice.instance().start(
        num_data=num_workers, num_server=num_servers, key_space=key_space
    )


def stop_system() -> None:
    _app_registry.clear()
    Postoffice.instance().stop()
    Postoffice.reset()


# Apps created by run_system, for group routing (ref: the manager's customer
# registry keyed by (node, customer id); here one process hosts every node).
_app_registry: List[App] = []

# RPC counter cached per registry epoch — re-resolved after a
# Postoffice.reset swaps the default registry, one .inc() otherwise
_rpc_counter = None
_rpc_registry = None


def _count_rpc() -> None:
    global _rpc_counter, _rpc_registry
    from .telemetry import registry as telemetry_registry

    if not telemetry_registry.enabled():
        return
    reg = telemetry_registry.default_registry()
    if reg is not _rpc_registry:
        from .telemetry.instruments import app_instruments

        _rpc_counter = app_instruments(reg)["rpcs"]
        _rpc_registry = reg
    _rpc_counter.inc()

_GROUP_ROLES = {
    NodeGroups.SERVER_GROUP: {Node.SERVER},
    NodeGroups.WORKER_GROUP: {Node.WORKER},
    NodeGroups.COMP_GROUP: {Node.SERVER, Node.WORKER},
    NodeGroups.LIVE_GROUP: {Node.SCHEDULER, Node.SERVER, Node.WORKER},
}


def _group_apps(recver: str) -> List[App]:
    roles = _GROUP_ROLES.get(recver)
    out = []
    for a in _app_registry:
        node = getattr(a, "node", None)
        if node is None:
            continue
        if (roles is not None and node.role in roles) or node.id == recver:
            out.append(a)
    return out


def submit(
    app: App,
    task: Optional[Task] = None,
    recver: str = NodeGroups.SERVER_GROUP,
    callback: Optional[Callable[[], None]] = None,
) -> int:
    """RPC-style Submit (ref customer.h ``Submit(task, NodeID)``): deliver a
    request carrying ``task`` to every app in the ``recver`` group (a
    NodeGroups constant or a node id like "S0"), invoking each receiver's
    ``process_request``; receivers that do not reply themselves are acked by
    the system (ref executor.cc). Returns the timestamp to ``app.wait`` on;
    ``callback`` fires when the last reply lands. Delivery is asynchronous
    (the step runs on the sender's executor dispatch thread, like the
    reference's per-customer engine): ``app.wait(ts)`` before relying on
    side effects or the callback having fired.
    """
    task = dataclasses.replace(task) if task is not None else Task()
    if task.time < 0:
        task.time = app.executor.time()
    # capture the sender identity on the CALLING thread — the step body runs
    # on the executor's dispatch thread (out-of-order engine), whose
    # thread-local node is not the submitting worker's
    me = _current_node()
    _count_rpc()

    def step() -> None:
        _set_current_node(me)
        # groups include the sender's own node when its role matches (ref
        # executor.cc AddNode: every node joins kLiveGroup and its role
        # group), so a broadcast delivers to self via loopback too
        for target in _group_apps(recver):
            # fresh_copy: each target's encode chain mutates the filter
            # specs' extra dicts (compression meta, key signatures) —
            # sharing them across targets or with the caller's Task races
            req = Message(
                task=task.fresh_copy(),
                sender=app.name,
                recver=target.node.id,
            )
            # the REAL send path, even for loopback delivery: the
            # sender's per-peer filter chain encodes, the message
            # serializes to wire bytes, and the receiver's chain decodes
            # (ref remote_node.cc: filters apply on every send/recv; the
            # reference serializes through ZMQ even between local
            # processes). Filters with per-peer state — key_caching
            # signatures, compression meta — therefore carry every ps.h
            # RPC, and the RemoteNode/Van counters measure real frames.
            req = app.po.van.transfer(
                app.remote_nodes.get(target.node.id),
                target.remote_nodes.get(app.name),
                req,
            )
            # the wire trace context re-activates on the RECEIVING side
            # (spans.activate_trace): the handler — and anything it
            # submits onto the receiver's executor — stays on the
            # request's flow, so one RPC is ONE flow across the Van
            # even when the receiver is a remote process
            with telemetry_spans.activate_trace(
                getattr(req.task, "trace", None)
            ):
                # each node's receive path is serialized (the reference
                # runs one executor thread per customer), so
                # hello-style apps may mutate unlocked state in
                # process_request
                with target._ps_recv_lock:
                    # the receiver's hooks run under its node identity
                    # (in the reference they run in the receiver's
                    # process)...
                    _set_current_node(target.node)
                    try:
                        target.process_request(req)
                    finally:
                        _set_current_node(me)
                # ...while the auto-ack delivers process_response
                # inline to the sender, which must see its own identity
                if not getattr(req, "replied", False):
                    target.reply(req)
            # message receipt doubles as a liveness signal (the reference
            # piggybacks heartbeat info on messages)
            target.po.beat(target.node.id)
        if callback is not None:
            callback()

    return app.submit(step, task=task)


def run_system(
    create_app: Callable[[], App],
    num_workers: Optional[int] = None,
    num_servers: int = 1,
    key_space: Optional[Range] = None,
) -> List[App]:
    """Run a ps.h-style program end to end (ref RunSystem + local.sh).

    ``create_app`` is called once per node — with ``is_worker()`` /
    ``is_server()`` / ``is_scheduler()`` answering for that node, exactly like
    the reference's ``App::Create`` factory — then every worker app's
    ``run()`` executes on its own thread (the reference's per-process main).
    Returns the app instances (scheduler first, then servers, then workers).
    """
    po = start_system(num_workers, num_servers, key_space)
    apps: List[App] = []
    try:
        for node in po.manager.nodes:
            _set_current_node(node)
            app = create_app()
            app.node = node
            app.name = node.id  # messages identify nodes by id (ref van.cc)
            # RLock: process_request may itself submit to a group that now
            # includes this node (self-delivery), re-entering the lock
            app._ps_recv_lock = threading.RLock()
            apps.append(app)
            _app_registry.append(app)
        workers = [a for a in apps if a.node.role == Node.WORKER]
        threads = []
        errors: List[BaseException] = []
        errors_lock = threading.Lock()
        for app in workers:

            def body(app: App = app) -> None:
                _set_current_node(app.node)
                try:
                    app.run()
                except BaseException as e:  # noqa: BLE001 — re-raised below
                    with errors_lock:
                        errors.append(e)

            t = threading.Thread(target=body, name=f"run_{app.node.id}")
            t.start()
            threads.append(t)
        for t in threads:
            t.join()
        if errors:
            # a crashed worker must fail the program, not vanish with its
            # thread (the reference's process exit code propagates)
            raise errors[0]
        for app in apps:
            if app.node.role != Node.WORKER:
                _set_current_node(app.node)
                app.run()
    finally:
        # drain every app's executor before tearing the registry down —
        # ps.submit is asynchronous, and a fire-and-forget broadcast still
        # enqueued on a dispatch thread must deliver before nodes vanish
        import sys

        unwinding = sys.exc_info()[0] is not None
        drain_errors: List[BaseException] = []
        for app in apps:
            try:
                app.executor.wait_all()
                app.executor.stop()
            except BaseException as e:  # noqa: BLE001 — collected below
                drain_errors.append(e)
        _set_current_node(None)
        stop_system()
        if drain_errors and not unwinding:
            # a fire-and-forget step crashed: fail the program like the
            # reference's process exit code would (but never mask an
            # exception already unwinding)
            raise drain_errors[0]
    return apps


# -- node identity helpers (ref ps.h MyApp/MyNode/MyNodeID/IsWorker/...) --


def my_app() -> Optional[App]:
    """The app running on the current node (ref ps.h MyApp)."""
    node = getattr(_tls, "node", None)
    if node is not None:
        for a in _app_registry:
            if getattr(a, "node", None) is node:
                return a
    po = Postoffice.instance()
    for c in list(po.manager._customers.values()):
        if isinstance(c, App):
            return c
    return None


def my_node() -> Node:
    return _current_node()


def my_node_id() -> str:
    return _current_node().id


def is_worker() -> bool:
    return _current_node().role == Node.WORKER


def is_server() -> bool:
    return _current_node().role == Node.SERVER


def is_scheduler() -> bool:
    return _current_node().role == Node.SCHEDULER


def my_key_range() -> Range:
    return _current_node().key_range


def scheduler_id() -> str:
    return "H0"


def next_customer_id() -> int:
    return Postoffice.instance().manager.next_customer_id()


def my_rank() -> int:
    return _current_node().rank


def rank_size() -> int:
    """Nodes in my group (ref ps.h RankSize)."""
    role = _current_node().role
    nodes = Postoffice.instance().manager.nodes
    return max(1, sum(1 for n in nodes if n.role == role))


# -- readiness barriers (ref ps.h WaitServersReady/WaitWorkersReady). On the
#    single-process SPMD runtime all nodes exist once start_system returns,
#    so these only assert the system is up. --


def wait_servers_ready() -> None:
    if not Postoffice.instance().started:
        raise RuntimeError("system not started (call start_system first)")


def wait_workers_ready() -> None:
    wait_servers_ready()
