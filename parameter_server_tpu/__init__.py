"""parameter_server_tpu — a TPU-native parameter-server framework.

A from-scratch rebuild of the DMLC parameter server (Mu Li et al., OSDI'14;
reference C++/ZMQ tree mounted at /root/reference) designed for TPU: sharded
parameter tables live in HBM over a ``jax.sharding.Mesh``, push/pull lower to
XLA collectives, hot update rules run as Pallas kernels, and the host control
plane (schedulers, readers, filters, recordio) mirrors the reference's C++
runtime with a C++ fast path of its own (``cpp/``).

Quick start::

    import parameter_server_tpu as pst

    po = pst.Postoffice.instance().start(num_server=1)
    w = pst.KVVector(name="w", num_slots=1024, k=1)
    ...

The ``ps`` module is the ps.h-style convenience façade for writing
role-dispatched apps; ``apps.linear.main`` is the conf-driven CLI.
"""

from . import ps
from .parameter.kv_layer import KVLayer
from .parameter.kv_map import KVMap
from .parameter.kv_store import kv_store
from .parameter.kv_vector import KVVector
from .system.customer import App, Customer
from .system.executor import NodeGroups
from .system.message import Message, Task
from .system.postoffice import Postoffice
from .utils.range import Range

__version__ = "0.1.0"

__all__ = [
    "App",
    "Customer",
    "KVLayer",
    "KVMap",
    "KVVector",
    "kv_store",
    "Message",
    "NodeGroups",
    "Postoffice",
    "Range",
    "Task",
    "ps",
    "__version__",
]
