"""CLI: python -m parameter_server_tpu.benchmarks [name ...] [--smoke]"""

from __future__ import annotations

import argparse
import sys

from . import REGISTRY
from . import components  # noqa: F401 — populates REGISTRY


def main(argv=None) -> int:
    from ..parallel.mesh import honor_jax_platforms

    honor_jax_platforms()  # JAX_PLATFORMS=cpu must win over the plugin
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "names",
        nargs="*",
        help=f"benchmarks to run (default all): {', '.join(sorted(REGISTRY))}",
    )
    ap.add_argument("--smoke", action="store_true", help="tiny quick run")
    args = ap.parse_args(argv)
    names = args.names or sorted(REGISTRY)
    for name in names:
        if name not in REGISTRY:
            ap.error(f"unknown benchmark {name!r}; have {sorted(REGISTRY)}")
        REGISTRY[name](args.smoke)
    return 0


if __name__ == "__main__":
    sys.exit(main())
