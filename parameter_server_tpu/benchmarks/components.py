"""Component benchmarks (see package docstring for the reference map)."""

from __future__ import annotations

import numpy as np

from . import HBM_PEAK_GB_S, benchmark, report, timeit


def _mesh():
    from ..system.postoffice import Postoffice

    Postoffice.reset()
    return Postoffice.instance().start().mesh


@benchmark("kv_vector")
def kv_vector_perf(smoke: bool = False) -> None:
    """Push/pull throughput of the sharded dense table
    (ref src/test/kv_vector_perf_ps.cc).

    Three paths are A/B'd at the kernel level on the SAME shapes:
    the seed's copying push (fresh [P, k] output per call), the donated
    in-place push, and the fused push→pull single-dispatch round trip —
    the zero-copy data plane's two wins, quoted with the structural
    bytes each donated push stops moving."""
    import jax

    from ..ops import kv_ops
    from ..parameter.kv_vector import KVVector

    mesh = _mesh()
    n_keys = 1 << (12 if smoke else 18)
    k = 4
    kv = KVVector(mesh=mesh, k=k, num_slots=2 * n_keys, hashed=True)
    keys = np.random.default_rng(0).integers(0, 1 << 40, n_keys).astype(np.int64)
    vals = np.ones((n_keys, k), np.float32)

    def push():
        kv.wait(kv.push(kv.request(channel=0), keys=keys, values=vals))

    def pull():
        jax.block_until_ready(kv.wait_pull(kv.pull(kv.request(channel=0), keys=keys)))

    def push_pull_fused():
        jax.block_until_ready(
            kv.wait_pull(kv.push_pull(kv.request(channel=0), keys=keys, values=vals))
        )

    def push_then_pull():
        kv.wait(kv.push(kv.request(channel=0), keys=keys, values=vals))
        jax.block_until_ready(kv.wait_pull(kv.pull(kv.request(channel=0), keys=keys)))

    n = 3 if smoke else 10
    sec = timeit(push, n)
    report("kv_vector_push_keys_per_sec", n_keys / sec, "keys/sec")
    report("kv_vector_push_mb_per_sec", vals.nbytes / sec / 1e6, "MB/s")
    sec = timeit(pull, n)
    report("kv_vector_pull_keys_per_sec", n_keys / sec, "keys/sec")

    # fused vs sequenced round trip (same store-level machinery both ways)
    sec = timeit(push_pull_fused, n)
    report("kv_vector_push_pull_fused_rt_per_sec", 1.0 / sec, "rt/sec")
    sec = timeit(push_then_pull, n)
    report("kv_vector_push_then_pull_rt_per_sec", 1.0 / sec, "rt/sec")

    # kernel-level donate/copy A/B: same jitted scatter-add, only the
    # aliasing differs — the delta IS the [P, k] table copy
    slots = jax.block_until_ready(kv.slots(0, keys))
    vjnp = jax.block_until_ready(jax.device_put(vals))
    table_copy = jax.block_until_ready(kv.table(0, copy=True))
    tbl_box = [kv.table(0, copy=True)]

    def push_nodonate():
        jax.block_until_ready(
            kv_ops.push(table_copy, slots, vjnp, mesh=mesh, batch_sharded=False)
        )

    def push_donated():
        tbl_box[0] = kv_ops.push_donated(
            tbl_box[0], slots, vjnp, mesh=mesh, batch_sharded=False
        )
        jax.block_until_ready(tbl_box[0])

    sec_nd = timeit(push_nodonate, n)
    report("kv_vector_push_nodonate_keys_per_sec", n_keys / sec_nd, "keys/sec")
    sec_d = timeit(push_donated, n)
    report("kv_vector_push_donated_keys_per_sec", n_keys / sec_d, "keys/sec")
    report(
        "kv_vector_push_copy_bytes_avoided_per_push",
        float(table_copy.nbytes),
        "bytes",
    )


@benchmark("kv_map")
def kv_map_perf(smoke: bool = False) -> None:
    """Entry-update throughput (ref src/test/kv_map_perf_ps.cc): vectorized
    FTRL entries over the sharded struct-of-arrays state."""
    from ..parameter.kv_map import AddEntry, KVMap

    mesh = _mesh()
    n_keys = 1 << (12 if smoke else 18)
    m = KVMap(AddEntry(), mesh=mesh, k=1, num_slots=2 * n_keys, hashed=True)
    keys = np.random.default_rng(0).integers(0, 1 << 40, n_keys).astype(np.int64)
    vals = np.ones((n_keys, 1), np.float32)

    def push():
        m.wait(m.push(m.request(), keys, vals))

    sec = timeit(push, 3 if smoke else 10)
    report("kv_map_entry_updates_per_sec", n_keys / sec, "entries/sec")


@benchmark("kv_layer")
def kv_layer_perf(smoke: bool = False) -> None:
    """Dense-layer push/pull throughput (ref src/test/kv_layer_perf_ps.cc).

    A/B: donated in-place updater (the default) vs the seed's copying
    updater (``donate=False``), plus the fused push_pull round trip."""
    import jax

    from ..parameter.kv_layer import KVLayer, SGDUpdater

    mesh = _mesh()
    shape = (256, 64) if smoke else (4096, 512)
    layer = KVLayer(partition_thr=1024, updater=SGDUpdater(lr=0.1), mesh=mesh)
    layer.init_layer("w", shape)
    grad = np.ones(shape, np.float32)
    nbytes = grad.nbytes

    def push():
        layer.wait(layer.push(layer.request(), "w", grad))

    def pull():
        jax.block_until_ready(layer.wait_pull(layer.pull(layer.request(), "w")))

    def push_pull_fused():
        jax.block_until_ready(
            layer.wait_pull(layer.push_pull(layer.request(), "w", grad))
        )

    n = 3 if smoke else 10
    report("kv_layer_push_mb_per_sec", nbytes / timeit(push, n) / 1e6, "MB/s")
    report("kv_layer_pull_mb_per_sec", nbytes / timeit(pull, n) / 1e6, "MB/s")
    sec = timeit(push_pull_fused, n)
    report("kv_layer_push_pull_fused_rt_per_sec", 1.0 / sec, "rt/sec")
    report("kv_layer_push_copy_bytes_avoided_per_push", float(nbytes), "bytes")

    # copying-mode A/B (the seed path): same updater, donation off
    nodon = KVLayer(
        partition_thr=1024, updater=SGDUpdater(lr=0.1), mesh=mesh,
        donate=False,
    )
    nodon.init_layer("w", shape)

    def push_nodonate():
        nodon.wait(nodon.push(nodon.request(), "w", grad))

    report(
        "kv_layer_push_nodonate_mb_per_sec",
        nbytes / timeit(push_nodonate, n) / 1e6,
        "MB/s",
    )

    def push_then_pull():
        layer.wait(layer.push(layer.request(), "w", grad))
        jax.block_until_ready(layer.wait_pull(layer.pull(layer.request(), "w")))

    sec = timeit(push_then_pull, n)
    report("kv_layer_push_then_pull_rt_per_sec", 1.0 / sec, "rt/sec")


@benchmark("network")
def network_perf(smoke: bool = False) -> None:
    """Wire latency/bandwidth by message size (ref
    src/test/network_perf_ps.cc): host→device transfer (the PCIe/tunnel
    hop) and the in-mesh psum collective."""
    import jax
    import jax.numpy as jnp
    from ..utils.compat import shard_map
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import DATA_AXIS

    mesh = _mesh()
    sizes_kb = [8, 64] if smoke else [8, 64, 1024, 8192]
    for kb in sizes_kb:
        x = np.ones(kb * 1024 // 4, np.float32)

        def h2d():
            jax.block_until_ready(jax.device_put(x))

        sec = timeit(h2d, 3 if smoke else 10)
        report(f"network_h2d_{kb}kb_ms", sec * 1e3, "ms")
        report(f"network_h2d_{kb}kb_mb_per_sec", x.nbytes / sec / 1e6, "MB/s")

    x = np.ones((64 if smoke else 1024) * 256, np.float32)
    xd = jax.device_put(x)
    psum = jax.jit(
        shard_map(
            lambda v: jax.lax.psum(v, DATA_AXIS),
            mesh=mesh,
            in_specs=P(),
            out_specs=P(),
            check_vma=False,
        )
    )
    jax.block_until_ready(psum(xd))

    def coll():
        jax.block_until_ready(psum(xd))

    sec = timeit(coll, 5 if smoke else 20)
    report("network_psum_ms", sec * 1e3, "ms")


@benchmark("sparse_matrix")
def sparse_matrix_perf(smoke: bool = False) -> None:
    """Host sparse-matrix pipeline (ref src/test/sparse_matrix_perf.cc):
    key uniquification (countUniqIndex), localization, and the device
    SpMV."""
    import jax
    import jax.numpy as jnp

    from ..utils.localizer import Localizer, count_uniq_keys
    from ..utils.sparse import random_sparse

    _mesh()
    n = 1 << (10 if smoke else 14)
    nnz = 64
    batch = random_sparse(n, 1 << 24, nnz, seed=0)

    def uniq():
        count_uniq_keys(batch)

    sec = timeit(uniq, 3 if smoke else 10)
    report("sparse_uniq_keys_per_sec", batch.nnz / sec, "keys/sec")

    loc = Localizer()
    keys, _ = loc.count_uniq_index(batch)

    def localize():
        loc.remap_index(keys)

    sec = timeit(localize, 3 if smoke else 10)
    report("sparse_localize_keys_per_sec", batch.nnz / sec, "keys/sec")

    local = loc.remap_index(keys)
    w = np.random.default_rng(0).normal(size=len(keys)).astype(np.float32)
    rows = local.row_ids().astype(np.int32)
    ucols = local.indices.astype(np.int32)
    vals = (
        np.ones(local.nnz, np.float32)
        if local.binary
        else local.values.astype(np.float32)
    )
    args = [jax.device_put(a) for a in (vals, ucols, rows, w)]
    # Xw = segment-sum over the localized COO — the XLA formulation the
    # fused app steps use (a Pallas spmv was probed and rejected: Mosaic
    # has no 1-D table gather; see SURVEY §3)
    fn = jax.jit(
        lambda v, c, r, w: jax.ops.segment_sum(v * w[c], r, num_segments=n)
    )
    jax.block_until_ready(fn(*args))

    def mv():
        jax.block_until_ready(fn(*args))

    sec = timeit(mv, 5 if smoke else 20)
    report("sparse_spmv_mnnz_per_sec", batch.nnz / sec / 1e6, "Mnnz/s")


@benchmark("attention")
def attention_perf(smoke: bool = False) -> None:
    """Flash-kernel vs XLA dense attention on one device (the per-chunk
    compute that ring/ulysses sequence parallelism schedules). Flushes by
    fetching a scalar — block_until_ready under-waits on the tunneled
    backend (see bench.py's measurement note). CHAIN attention calls run
    inside one jitted lax.scan (each feeding its output back as the next
    query, so nothing dead-codes): one launch per timed rep costs a
    tunnel round trip that would otherwise swamp the kernel — the first
    on-chip capture measured both paths at an identical 195 GFLOP/s,
    i.e. pure dispatch latency."""
    import jax

    from ..ops.flash_attention import _use_pallas, flash_attention

    bh = 4
    s = 512 if smoke else 4096
    d = 64
    chain = 2 if smoke else 16
    rng = np.random.default_rng(0)
    q, k, v = (
        jax.device_put(rng.normal(size=(bh, s, d)).astype(np.float32))
        for _ in range(3)
    )

    def make_run(use_pallas, dtype=np.float32):
        # jit the whole chain so the XLA path is the FUSED program the
        # model paths embed, not an eager per-op chain
        @jax.jit
        def fn(q0, kk, vv):
            def body(qc, _):
                o = flash_attention(
                    qc, kk, vv, causal=True, use_pallas=use_pallas,
                    interpret=False if use_pallas else None,
                )
                return o.astype(qc.dtype), None

            qf, _ = jax.lax.scan(body, q0, None, length=chain)
            return qf

        args = [x.astype(dtype) for x in (q, k, v)]

        def run():
            np.asarray(fn(*args)[0, 0, 0], np.float32)  # true flush

        return run

    # 2 matmuls, causal ~half but count full (the convention MFU tables use)
    flops = 4.0 * bh * s * s * d * chain
    n = 2 if smoke else 10
    sec = timeit(make_run(False), n)
    report("attention_xla_gflops", flops / sec / 1e9, "GFLOP/s")
    if _use_pallas():  # Mosaic on TPU only (interpret is not a perf path)
        sec = timeit(make_run(True), n)
        report("attention_flash_gflops", flops / sec / 1e9, "GFLOP/s")
        # bf16 inputs (fp32 accumulation in-kernel): the dtype the LM
        # decoder actually feeds, and the MXU's native input width
        sec = timeit(make_run(True, np.dtype("bfloat16")), n)
        report("attention_flash_bf16_gflops", flops / sec / 1e9, "GFLOP/s")
        sec = timeit(make_run(False, np.dtype("bfloat16")), n)
        report("attention_xla_bf16_gflops", flops / sec / 1e9, "GFLOP/s")


@benchmark("step_phases")
def step_phases_perf(smoke: bool = False) -> None:
    """Each phase of the fused async-SGD bits step as its OWN jitted
    program at the headline bench shapes (rows 16384 x 39 lanes), at
    BOTH headline table sizes — 2^22 slots (synthetic bench) and 2^26
    (--real criteo) — the decomposition of bench.py's ~26-32 ms device
    step.

    The r3 sweep data shows the device-only rate is step-bound, not
    dispatch-bound (T=8->32 moved it 1%), while the step's HBM traffic
    justifies <1 ms: one of these phases is eating ~95% of the time,
    and this bench names it even if the axon backend's profiler traces
    turn out unparseable (insurance for --profile). Phase sum !=
    fused-step time exactly (XLA fuses across phase boundaries), but a
    300x structural outlier dwarfs that error bar.
    """
    rows, lanes = (1024, 8) if smoke else (16384, 39)
    # both headline table sizes: 2^22 (synthetic bench) and 2^26
    # (--real criteo) — the structural loss may be size-dependent
    # (gather working set 16 MB vs 256 MB spans VMEM-resident to
    # HBM-bound regimes)
    for num_slots in ([1 << 14] if smoke else [1 << 22, 1 << 26]):
        _step_phases_at(rows, lanes, num_slots, smoke)


def _step_phases_at(
    rows: int, lanes: int, num_slots: int, smoke: bool
) -> None:
    import jax
    import jax.numpy as jnp

    from ..apps.linear.learning_rate import LearningRate
    from ..apps.linear.penalty import ElasticNet
    from ..apps.linear.updaters import FTRLUpdater
    from ..utils.bitpack import (
        pack_bits,
        slot_bits,
        stream_to_words,
        unpack_bits,
        unpack_sign_bits,
    )

    tag = f"_s{num_slots.bit_length() - 1}"
    bits = slot_bits(num_slots)
    rng = np.random.default_rng(0)

    slots_host = rng.integers(0, num_slots, rows * lanes, np.int64)
    # the SAME <u4 word layout the production decode consumes
    # (async_sgd.py unpack path): a raw byte stream would make the
    # timed gathers byte-granular and the decode verdict wrong
    words = jax.device_put(
        stream_to_words(pack_bits(slots_host, bits), rows * lanes, bits)
    )
    y_bits = jax.device_put(
        np.packbits(rng.integers(0, 2, rows).astype(np.uint8))
    )
    updater = FTRLUpdater(
        LearningRate(type_=LearningRate.DECAY, alpha=0.1, beta=1.0),
        ElasticNet(1.0, 0.0),
    )
    state = {
        "z": jax.device_put(
            rng.normal(size=num_slots).astype(np.float32)
        ),
        "sqrt_n": jax.device_put(
            np.abs(rng.normal(size=num_slots)).astype(np.float32)
        ),
    }
    rel = jax.device_put(slots_host.astype(np.int32))
    gr = jax.device_put(rng.normal(size=rows).astype(np.float32))
    grad = jax.device_put(rng.normal(size=num_slots).astype(np.float32))

    def timed_phase(name, fn, *args):
        jf = jax.jit(fn)
        jax.block_until_ready(jf(*args))  # compile untimed
        # tight per-phase budget: 12 phases x 2 sizes through the
        # tunnel must fit the watcher's components timeout (the
        # un-budgeted schedule blew a 2400s suite timeout once —
        # timeit docstring)
        n = 3 if smoke else 10
        sec = timeit(
            lambda: jax.block_until_ready(jf(*args)), n, budget_s=25.0
        )
        report(f"step_phase_{name}{tag}_ms", sec * 1e3, "ms")
        return sec

    total = 0.0
    total += timed_phase(
        "decode",
        lambda w, yb: (
            unpack_bits(w, rows * lanes, bits),
            unpack_sign_bits(yb, rows),
        ),
        words, y_bits,
    )
    total += timed_phase(
        "weights_dense", lambda st: updater.weights(st), state
    )

    # gather timed on a PRECOMPUTED dense weight vector: the dense
    # transform is already its own phase above, and the production
    # updater.weights is reused rather than re-derived
    w_dense = jax.block_until_ready(jax.jit(updater.weights)(state))
    total += timed_phase(
        "gather_sum",
        lambda w, idx: w[idx].reshape(rows, lanes).sum(axis=1),
        w_dense, rel,
    )
    total += timed_phase(
        "scatter_add",
        lambda idx, g: jnp.zeros((num_slots,), jnp.float32)
        .at[idx]
        .add(jnp.broadcast_to(g[:, None], (rows, lanes)).reshape(-1)),
        rel, gr,
    )
    # the ftrl phase must time the PRODUCTION configuration: the fused
    # step donates the table and the kernel updates it in place
    # (ops/ftrl.py input_output_aliases), with membership derived from
    # grad's support (touched=None, the unquantized-push contract). A
    # non-donated call would instead time kernel + XLA's defensive
    # whole-table copies — a different program than the one shipped.
    jf_ftrl = jax.jit(
        lambda st, g: updater.apply(st, g, None, seed=np.uint32(1)),
        donate_argnums=(0,),
    )
    st_ftrl = jax.tree.map(jnp.copy, state)
    st_ftrl = jax.block_until_ready(jf_ftrl(st_ftrl, grad))
    _st_box = [st_ftrl]

    def _ftrl_once():
        _st_box[0] = jf_ftrl(_st_box[0], grad)
        jax.block_until_ready(_st_box[0])

    sec = timeit(_ftrl_once, 3 if smoke else 10, budget_s=25.0)
    report(f"step_phase_ftrl_update{tag}_ms", sec * 1e3, "ms")
    total += sec
    report(f"step_phase_sum{tag}_ms", total * 1e3, "ms")
    report(
        f"step_phase_sum{tag}_equiv_examples_per_sec",
        rows / total,
        "examples/sec",
    )


def _write_synth_libsvm(path: str, rows: int, lanes: int, seed: int = 0) -> None:
    """Synthetic libsvm text: ``rows`` examples x ``lanes`` sorted
    uint features, ±1 labels — the criteo-like shape the headline bench
    streams."""
    rng = np.random.default_rng(seed)
    keys = np.sort(rng.integers(0, 1 << 31, (rows, lanes)), axis=1)
    labels = rng.choice((-1, 1), rows)
    with open(path, "w") as f:
        for i in range(rows):
            f.write(
                f"{labels[i]} "
                + " ".join(f"{k}:1" for k in keys[i])
                + "\n"
            )


def host_ingest_ab(
    smoke: bool = False, workers: "int | None" = None
) -> dict:
    """Serial-vs-pipelined host-ingest A/B (HOST side only, no device).

    Both arms ingest the same libsvm file at the headline bench shape
    (16384-row x 39-lane criteo-like batches) through the same
    exact-wire prep (``prep_batch``: unique → inverse-remap → pad).
    The **serial** arm is the seed MinibatchReader critical path:
    line-based parse + prep inline on the caller's thread, batch by
    batch. The **pipelined** arm is the PR's staged ingest plane end to
    end: chunked byte parse (``StreamReader.minibatches_bytes`` — raw
    chunks into the GIL-releasing native parser on a small pool)
    feeding ``learner.ingest.IngestPipeline``'s ordered prep workers —
    the consumer just drains, like a trainer whose thread is free for
    device dispatch. The countmin tail-filter is deliberately absent
    from BOTH arms: it is off in the default config
    (``tail_feature_freq=0``) and, being stateful, would run serially
    on the feeder either way. Arms run strictly alternating and the
    quoted rates aggregate over all reps — this host's effective CPU
    capacity flaps on a seconds timescale (sandboxed kernel), so
    single-shot or best-of numbers are a lottery. Returns the dict
    ``bench.py`` embeds under ``host_ingest``; batch streams are
    bit-identical across arms (tier-1 parity test in
    tests/test_ingest.py)."""
    import os
    import tempfile
    import time as _time

    from ..apps.linear.async_sgd import prep_batch
    from ..data.stream_reader import StreamReader
    from ..learner.ingest import IngestPipeline
    from ..parameter.parameter import KeyDirectory

    # smoke stays criteo-lane-shaped but smaller; going much below this
    # makes per-rep work so short that thread spin-up and capacity
    # flaps swamp the overlap being measured
    rows_per_batch = 8192 if smoke else 16384
    n_batches = 4 if smoke else 6
    lanes = 24 if smoke else 39
    num_shards = 2
    num_slots = 1 << 22
    if workers is None:
        workers = max(2, min(4, os.cpu_count() or 2))
    directory = KeyDirectory(num_slots, hashed=True)
    rows_pad = -(-rows_per_batch // num_shards)
    nnz_pad = rows_pad * lanes

    def prep(b):
        return prep_batch(
            b, directory, num_shards, rows_pad, nnz_pad, nnz_pad, num_slots
        )

    with tempfile.TemporaryDirectory() as td:
        path = f"{td}/ingest_ab.libsvm"
        _write_synth_libsvm(path, rows_per_batch * n_batches, lanes)

        def run_serial() -> float:
            n_ex = 0
            t0 = _time.perf_counter()
            for b in StreamReader([path], "libsvm").minibatches(
                rows_per_batch
            ):
                n_ex += prep(b).num_examples
            sec = _time.perf_counter() - t0
            assert n_ex == rows_per_batch * n_batches, n_ex
            return sec

        def run_pipelined() -> float:
            # 3 parse threads / capacity 8: measured sweet spot on the
            # 2-core host — the deep buffer rides out capacity flaps
            # (a shallow one stalls the prep pool at every hiccup)
            src = StreamReader([path], "libsvm").minibatches_bytes(
                rows_per_batch, chunk_bytes=2 << 20, threads=3
            )
            pipe = IngestPipeline(
                src,
                prep_fn=prep,
                workers=workers,
                capacity=8,
                name="host_ingest_ab",
            ).start()
            n_ex = 0
            t0 = _time.perf_counter()
            for p in pipe:
                n_ex += p.num_examples
            sec = _time.perf_counter() - t0
            assert n_ex == rows_per_batch * n_batches, n_ex
            return sec

        # one shared warm pass heats the file/prep caches, then the
        # arms run in back-to-back (pipelined, serial) pairs: the two
        # members of a pair see the same machine state, so the MEDIAN
        # pair ratio isolates the pipelining effect from capacity
        # flaps, while the quoted per-arm rates aggregate all reps
        run_serial()
        reps = 5
        sers, pips = [], []
        for _ in range(reps):
            pips.append(run_pipelined())
            sers.append(run_serial())
    per_rep = rows_per_batch * n_batches
    n_ex = per_rep * reps
    ratios = sorted(s / p for s, p in zip(sers, pips))
    return {
        "examples": n_ex,
        "minibatch": rows_per_batch,
        "lanes": lanes,
        "workers": workers,
        "reps": reps,
        "serial_examples_per_sec": round(n_ex / sum(sers), 1),
        "pipelined_examples_per_sec": round(n_ex / sum(pips), 1),
        # median of paired ratios (see measurement note above)
        "pipelined_speedup": round(ratios[len(ratios) // 2], 3),
    }


def _criteo_shape_batches(
    rows: int, lanes: int, n_batches: int, valued: bool = False,
    seed: int = 0,
):
    """Synthetic batches following the headline bench's data law
    (bench.py _write_criteo_chunk): 13 small-vocab integer fields +
    26 power-law (cube-of-uniform) categorical fields, field-salted
    keys, ±1 labels — the distribution the recorded 107.4 B/example
    baseline was measured on. ``valued`` attaches float values (the
    quantized-wire arm; the binary CTR stream has no value bytes)."""
    from ..utils.sparse import SparseBatch

    rng = np.random.default_rng(seed)
    n_int = min(13, lanes)
    n_cat = lanes - n_int
    out = []
    for _ in range(n_batches):
        ints = rng.integers(10, 100, size=(rows, n_int))
        u = rng.random((rows, n_cat))
        cats = (u * u * u * (1 << 24)).astype(np.int64)
        # field-salted keys: distinct key spaces per field, like the
        # criteo parser's (field, token) hash
        keys = np.concatenate(
            [
                (j << 40) | ints[:, j : j + 1] for j in range(n_int)
            ] + [
                ((100 + j) << 40) | cats[:, j : j + 1] for j in range(n_cat)
            ],
            axis=1,
        ).astype(np.int64)
        y = rng.choice((-1.0, 1.0), rows).astype(np.float32)
        vals = (
            (rng.random(rows * lanes) + 0.5).astype(np.float32)
            if valued else None
        )
        out.append(SparseBatch(
            y=y,
            indptr=np.arange(0, rows * lanes + 1, lanes),
            indices=keys.ravel(),
            values=vals,
        ))
    return out


# signature-only wire cost of an upload-cache hit: crc32c (4B) +
# shape/dtype routing metadata — what a repeated array actually costs
# the link (filter/key_caching.py semantics)
_SIG_BYTES = 16


def wire_ab(smoke: bool = False) -> dict:
    """Encoded-vs-raw compact-wire A/B (HOST side only, no device).

    Measures what each wire format ships per example at the headline
    bench shape, on data following the headline generator's law, plus
    the encode cost and the exact-mode parity bit. Arms:

    - ``raw_exact``  — the raw exact (host-dedup) PreppedBatch buffers
    - ``exact``      — learner/wire.encode_exact, lossless default mode
      (decode verified BIT-IDENTICAL here, every batch)
    - ``bits``       — the ELL bits wire (today's e2e default; this is
      the recorded 107.4 B/example raw baseline at 2^22 slots)
    - ``raw_valued``/``int8_valued`` — the valued stream raw vs
      fixed-point (the lossy mode, logloss-gated in tests)

    Multi-pass amortization: CTR training makes ``num_data_pass``
    passes over the shard, and pass ≥2 re-ships only crc32c signatures
    through the upload key cache (learner/wire.UploadCache, exact-
    verified) — ``amortized_bytes_per_example`` quotes the per-pass
    average with the pass count disclosed; the single-pass numbers
    stand alone above it. Encode throughput quotes the MEDIAN of
    back-to-back paired reps (the PR-3 bench discipline: this host's
    CPU capacity flaps on a seconds timescale)."""
    import time as _time

    from ..apps.linear.async_sgd import (
        prep_batch_ell_bits,
        prep_batch_ell_stream,
        prep_batch_shared,
    )
    from ..learner.wire import (
        UploadCache,
        compress_batch,
        decode_exact_host,
        decode_stream_shard,
        derive_stream_statics,
        encode_exact,
        tree_nbytes,
    )
    from ..parameter.parameter import KeyDirectory
    from ..utils.murmur import hash_slots

    rows = 4096 if smoke else 16384
    lanes = 39
    n_batches = 2 if smoke else 4
    passes = 3
    num_shards = 2
    num_slots = 1 << 22
    directory = KeyDirectory(num_slots, hashed=True)
    rows_pad = rows // num_shards
    nnz_pad = rows_pad * lanes
    uniq_pad = -(-min(nnz_pad * num_shards, num_slots) // 1024) * 1024

    batches = _criteo_shape_batches(rows, lanes, n_batches)
    n_ex = rows * n_batches

    def prep(b):
        return prep_batch_shared(
            b, directory, num_shards, rows_pad, nnz_pad, uniq_pad,
            num_slots,
        )

    # -- bytes per example, per encoding (with exact-mode parity) --
    raws = [prep(b) for b in batches]
    encs = [encode_exact(p, num_slots) for p in raws]
    assert all(e is not None for e in encs)
    parity = True
    for p, e in zip(raws, encs):
        dec = decode_exact_host(e, num_slots)
        import dataclasses as _dc

        for f, arr in zip(_dc.fields(type(p)), dec):
            want = np.asarray(getattr(p, f.name))
            parity &= bool(
                want.dtype == np.asarray(arr).dtype
                and np.array_equal(want, np.asarray(arr))
            )
    bits = [
        prep_batch_ell_bits(
            b, directory, num_shards, rows_pad, lanes, num_slots
        )
        for b in batches
    ]
    assert all(x is not None for x in bits)

    # -- the stream-once lane-dictionary wire (cache-free arm): statics
    # pinned from the first batch exactly like the worker does, decode
    # verified bit-identical against the hashed slot matrix --
    st = derive_stream_statics(
        batches[0].indices, lanes, num_slots, num_slots
    )
    streams = [
        prep_batch_ell_stream(
            b, directory, num_shards, rows_pad, lanes, num_slots, st
        )
        for b in batches
    ]
    stream_parity = st is not None and all(s is not None for s in streams)
    if stream_parity:
        for b, s in zip(batches, streams):
            per = -(-b.n // num_shards)
            for d in range(num_shards):
                lo, hi = min(d * per, b.n), min((d + 1) * per, b.n)
                seg = slice(b.indptr[lo], b.indptr[hi])
                want = hash_slots(
                    np.ascontiguousarray(b.indices[seg], np.uint64),
                    num_slots,
                ).reshape(hi - lo, lanes)
                y, mask, slots = decode_stream_shard(s, d)
                stream_parity &= bool(
                    np.array_equal(np.asarray(slots)[: hi - lo], want)
                    and np.array_equal(
                        np.asarray(y)[: hi - lo], b.y[lo:hi]
                    )
                )
    bpe = {
        "raw_exact": sum(tree_nbytes(p) for p in raws) / n_ex,
        "exact": sum(tree_nbytes(e) for e in encs) / n_ex,
        "bits": sum(tree_nbytes(x) for x in bits) / n_ex,
        **(
            {"stream": sum(tree_nbytes(s) for s in streams) / n_ex}
            if stream_parity
            else {}
        ),
    }

    # staging-leg codec per encoding (net of compression, utils/codec —
    # incompressible streams ride raw so the worst case is ~free):
    # quoted separately from bpe because it shrinks the host↔host
    # staging leg, NOT the PJRT host→device tunnel bytes
    lz_bpe = {}
    for name, parts in (
        ("exact", encs),
        ("bits", bits),
        *((("stream", streams),) if stream_parity else ()),
    ):
        lz_bpe[name] = round(
            sum(compress_batch(p).wire_nbytes for p in parts) / n_ex, 1
        )

    # valued stream: raw f32 vs int8 fixed-point (the lossy mode)
    vbatches = _criteo_shape_batches(rows, lanes, n_batches, valued=True,
                                     seed=1)
    vraws = [prep(b) for b in vbatches]
    vencs = [encode_exact(p, num_slots, mode="int8") for p in vraws]
    assert all(e is not None for e in vencs)
    bpe["raw_valued"] = sum(tree_nbytes(p) for p in vraws) / n_ex
    bpe["int8_valued"] = sum(tree_nbytes(e) for e in vencs) / n_ex

    # -- multi-pass amortization through the upload key cache --
    def amortize(parts):
        shipped = 0
        cache = UploadCache(upload_leaf=lambda leaf: leaf,
                            max_bytes=1 << 30)
        for _ in range(passes):
            for part in parts:
                b0, h0 = cache.saved_bytes, cache.hits
                cache(part)
                shipped += tree_nbytes(part) - (cache.saved_bytes - b0)
                shipped += _SIG_BYTES * (cache.hits - h0)
        return shipped / (n_ex * passes), cache

    amort_exact, cache_e = amortize(encs)
    amort_bits, cache_b = amortize(bits)
    amortized = {
        "exact_cached": round(amort_exact, 1),
        "bits_cached": round(amort_bits, 1),
    }

    # -- encode cost: median of back-to-back (prep, prep+encode) pairs --
    reps = 3 if smoke else 5
    t_prep, t_enc = [], []
    for _ in range(reps):
        t0 = _time.perf_counter()
        for b in batches:
            prep(b)
        t_prep.append(_time.perf_counter() - t0)
        t0 = _time.perf_counter()
        for b in batches:
            encode_exact(prep(b), num_slots)
        t_enc.append(_time.perf_counter() - t0)
    ratios = sorted(e / p for e, p in zip(t_enc, t_prep))

    raw_baseline = bpe["bits"]  # the recorded 107.4 B/ex configuration
    out = {
        "minibatch": rows,
        "lanes": lanes,
        "num_slots": num_slots,
        "batches": n_batches,
        "passes": passes,
        "bytes_per_example": {k: round(v, 1) for k, v in bpe.items()},
        "amortized_bytes_per_example": amortized,
        "raw_baseline_bytes_per_example": round(raw_baseline, 1),
        # the acceptance ratios, vs the recorded 107.4 B/ex baseline,
        # amortized over the disclosed pass count. Named precisely:
        # "lossless_default" is the e2e default BITS wire + the upload
        # key cache (the cache is the cross-batch half of the exact/
        # lossless contract — the bits stream itself is unchanged);
        # "exact_encode" is the new encoded exact (PreppedBatch) wire
        # under the same cache. Per-batch encode ratios are reported
        # separately below against each wire's own raw form.
        "reduction_vs_raw_baseline": {
            "lossless_default_amortized": round(
                raw_baseline / amort_bits, 2
            ),
            "exact_encode_amortized": round(
                raw_baseline / amort_exact, 2
            ),
            # the CACHE-FREE column (stream-once data gets no cache
            # repeats — the production --real regime): single-pass
            # bytes, no UploadCache anywhere in the arm
            **(
                {
                    "stream_cache_free": round(
                        raw_baseline / bpe["stream"], 2
                    )
                }
                if stream_parity
                else {}
            ),
        },
        "lz_staging_bytes_per_example": lz_bpe,
        "stream_parity_bit_identical": bool(stream_parity),
        "exact_reduction_vs_raw_exact": round(
            bpe["raw_exact"] / bpe["exact"], 2
        ),
        "int8_reduction_vs_raw_valued": round(
            bpe["raw_valued"] / bpe["int8_valued"], 2
        ),
        "exact_parity_bit_identical": bool(parity),
        "cache": {
            "hits": cache_e.hits + cache_b.hits,
            "misses": cache_e.misses + cache_b.misses,
            "saved_mb": round(
                (cache_e.saved_bytes + cache_b.saved_bytes) / 1e6, 1
            ),
        },
        "encode_over_prep_median_ratio": round(
            ratios[len(ratios) // 2], 3
        ),
        "prep_examples_per_sec": round(n_ex * reps / sum(t_prep), 1),
        "prep_encode_examples_per_sec": round(n_ex * reps / sum(t_enc), 1),
    }
    out["fused_prep"] = stream_prep_ab(smoke)
    return out


def stream_prep_ab(smoke: bool = False) -> dict:
    """Native-vs-Python fused stream-prep A/B (HOST side only).

    The stream wire's prep is the named multi-ms host stage fused into
    one C ABI call (``ps_stream_encode``: hash → per-lane unique →
    remap → bit-pack); the Python arm is the NumPy path it replaces
    (hash pass, per-lane ``np.unique``/``searchsorted`` passes, then
    the bit-packer). Both arms produce BYTE-IDENTICAL wire buffers
    (asserted here, every rep) — the native lib is a speedup, never a
    format. Quotes the MEDIAN of back-to-back paired reps with both
    arms disclosed (the bench discipline: this host's CPU capacity
    flaps seconds-scale). Without ``libpsnative`` the native arm is
    absent and the dict says so (``native_available``)."""
    import time as _time

    from ..cpp import native
    from ..learner import wire as wire_mod
    from ..learner.wire import derive_stream_statics, encode_stream_shard
    from ..utils.murmur import hash_slots

    rows = 4096 if smoke else 16384
    lanes = 39
    num_slots = 1 << 22
    b = _criteo_shape_batches(rows, lanes, 1, seed=3)[0]
    keys = np.ascontiguousarray(b.indices, np.uint64)
    st = derive_stream_statics(keys, lanes, num_slots, num_slots)
    assert st is not None, "criteo-law data must take the lane dictionary"
    lib = native()
    native_ok = (
        lib is not None and getattr(lib, "ps_stream_encode", None) is not None
    )
    out = {
        "minibatch": rows,
        "lanes": lanes,
        "num_slots": num_slots,
        "native_available": bool(native_ok),
        "dict_lanes": len(st.dict_lanes),
    }

    def run_py():
        return wire_mod._encode_stream_shard_py(
            hash_slots(keys, num_slots), rows, rows, st
        )

    def run_native():
        return encode_stream_shard(keys, rows, rows, num_slots, st)

    # parity first: byte-identical output, every field, before any
    # timing is quoted (the fallback contract)
    ref = run_py()
    assert ref is not None
    if native_ok:
        nat = run_native()
        for a, c in zip(nat, ref):
            assert np.array_equal(np.asarray(a), np.asarray(c)), (
                "native fused prep diverged from the Python path"
            )

    reps = 3 if smoke else 5
    t_py, t_nat = [], []
    for _ in range(reps):
        t0 = _time.perf_counter()
        run_py()
        t_py.append(_time.perf_counter() - t0)
        if native_ok:
            t0 = _time.perf_counter()
            run_native()
            t_nat.append(_time.perf_counter() - t0)
    py_ms = sorted(t_py)[len(t_py) // 2] * 1e3
    out["python_ms_median"] = round(py_ms, 3)
    out["python_examples_per_sec"] = round(rows / (py_ms / 1e3), 1)
    out["reps"] = reps
    if native_ok:
        nat_ms = sorted(t_nat)[len(t_nat) // 2] * 1e3
        out["native_ms_median"] = round(nat_ms, 3)
        out["native_examples_per_sec"] = round(rows / (nat_ms / 1e3), 1)
        out["speedup_median_paired"] = round(py_ms / nat_ms, 2)
        out["parity_byte_identical"] = True
    return out


@benchmark("stream_prep")
def stream_prep_perf(smoke: bool = False) -> None:
    """Native-vs-Python fused stream-prep A/B (see stream_prep_ab):
    one C ABI call (hash→unique→remap→bit-pack) against the NumPy
    passes it replaces, byte-identical output asserted."""
    out = stream_prep_ab(smoke)
    report(
        "stream_prep_python_examples_per_sec",
        out["python_examples_per_sec"], "examples/sec",
    )
    if out["native_available"]:
        report(
            "stream_prep_native_examples_per_sec",
            out["native_examples_per_sec"], "examples/sec",
        )
        report(
            "stream_prep_speedup_median_paired",
            out["speedup_median_paired"], "x",
        )


@benchmark("wire")
def wire_perf(smoke: bool = False) -> None:
    """Compact-wire encoded-vs-raw A/B (see wire_ab). CPU-only — bytes
    and encode cost; the link-bound ceiling each bytes/example implies
    is attached by bench.py from its measured link MB/s."""
    out = wire_ab(smoke)
    for k, v in out["bytes_per_example"].items():
        report(f"wire_bytes_per_example_{k}", v, "bytes")
    for k, v in out["amortized_bytes_per_example"].items():
        report(
            f"wire_amortized_bytes_per_example_{k}", v,
            f"bytes ({out['passes']} passes)",
        )
    for k, v in out["reduction_vs_raw_baseline"].items():
        report(f"wire_{k}_reduction_vs_raw_baseline", v, "x")
    report(
        "wire_encode_over_prep_median_ratio",
        out["encode_over_prep_median_ratio"], "x",
    )


@benchmark("host_ingest")
def host_ingest_perf(smoke: bool = False) -> None:
    """Serial vs pipelined host-ingest throughput (see host_ingest_ab).
    CPU-only — no mesh, no device: this isolates the ingest plane the
    way network_perf isolates the wire."""
    out = host_ingest_ab(smoke)
    report(
        "host_ingest_serial_examples_per_sec",
        out["serial_examples_per_sec"],
        "examples/sec",
    )
    report(
        "host_ingest_pipelined_examples_per_sec",
        out["pipelined_examples_per_sec"],
        "examples/sec",
    )
    report("host_ingest_pipelined_speedup", out["pipelined_speedup"], "x")


@benchmark("executor")
def executor_perf(smoke: bool = False) -> None:
    """Host-side dispatch overhead of the executor runtime (the
    counterpart of the reference's per-message Customer/Executor path,
    src/system/executor.cc) — CPU-measurable: how many trivial steps
    per second the submit → dependency-check → dispatch-thread →
    wait machinery moves, with and without dependency chains. The
    device-facing loops batch T minibatches per submit precisely
    because this ceiling exists; the number prices that design
    choice."""
    from ..system.executor import Executor, Task

    n = 500 if smoke else 5000

    ex = Executor("bench")

    def burst_independent():
        ts = [ex.submit(lambda: None) for _ in range(n)]
        ex.wait(ts[-1])
        for t in ts[:-1]:
            ex.wait(t)

    sec = timeit(burst_independent, 1 if smoke else 3)
    report("executor_dispatch_steps_per_sec", n / sec, "steps/sec")

    ex2 = Executor("bench-chain")

    def burst_chained():
        prev = ex2.submit(lambda: None)
        for _ in range(n - 1):
            prev = ex2.submit(lambda: None, task=Task(wait_time=[prev]))
        ex2.wait(prev)

    sec = timeit(burst_chained, 1 if smoke else 3)
    report("executor_chained_steps_per_sec", n / sec, "steps/sec")


def _serve_store(num_slots: int, key_space: int, seed: int = 0):
    """A trained-looking KVVector weight table + a power-law key draw
    (the serving workload shape: a small hot set carries most traffic)."""
    from ..parameter.kv_vector import KVVector

    mesh = _mesh()
    kv = KVVector(
        mesh=mesh, k=1, num_slots=num_slots, hashed=True, name="serve_w"
    )
    rng = np.random.default_rng(seed)
    warm_keys = np.unique(rng.integers(0, key_space, 4096))
    vals = rng.normal(size=(len(warm_keys), 1)).astype(np.float32)
    kv.wait(kv.push(kv.request(channel=0), keys=warm_keys, values=vals))

    # cube-of-uniform power law (the criteo-ish hot-key shape) over the
    # key space — requests OVERLAP heavily on the hot head, which is
    # what coalescing and the hot replica monetize. PRE-DRAWN pool: the
    # arrival thread must sustain thousands of submits/sec, and a fresh
    # Generator per request would throttle the offered load itself
    # (repeating key arrays also exercise the slot-signature caches the
    # way real repeated request shapes do)
    u = rng.random((256, 64))
    pool = (u * u * u * key_space).astype(np.int64)

    def draw_keys(i: int, n: int = 16) -> np.ndarray:
        return pool[i % len(pool), :n]

    return kv, draw_keys


def serve_ab(smoke: bool = False) -> dict:
    """Latency-first serving bench: open-loop Poisson load against the
    request-path frontend (serving/ — doc/SERVING.md).

    Four sections, one dict (embedded by bench.py under ``serve``):

    - **capacity**: closed-loop calibration of this host's per-request
      cost (replica-served pulls), from which the offered-load points
      are derived — the bench self-scales instead of hardcoding rates
      this flapping host would invalidate.
    - **points**: open-loop runs at ~0.25x capacity and ~3x capacity
      (overload) WITH admission control: the acceptance claim is that
      overload p99 stays within a small factor of the low-load p99
      because the door sheds (``shed_frac > 0``) instead of queueing.
    - **no_admission_overload**: the same overload WITHOUT admission —
      the p99 collapse the controller exists to prevent, quoted so the
      win is a measured A/B, not an assertion.
    - **coalesce**: concurrent overlapping-key pulls through the
      frontend (replica off, so every pull rides the live-table path):
      ``submits_per_request < 1`` is the executor-relief win, and
      ``key_dedup_factor`` the gather-volume win.
    - **decode**: the LM lane — speculative decoding
      (models/speculative.py over ops/flash_attention.py) served as
      DecodeRequests. Tiny random-init models on CPU (wiring + latency
      accounting; the TRAINED speedup evidence lives in BENCH_ONCHIP's
      serve/spec_big tasks: 2.33x bandwidth-bound).

    Open-loop + percentiles per the bench discipline: quoting a mean
    under overload would hide exactly the tail the SLO bounds.
    """
    import time as _time

    from ..serving import (
        DecodeRequest,
        PullRequest,
        ServeConfig,
        ServeFrontend,
        open_loop_bench,
    )

    num_slots = 1 << (12 if smoke else 16)
    key_space = 1 << 20
    keys_per_req = 16
    kv, draw_keys = _serve_store(num_slots, key_space)

    # every frontend below closes through ONE finally: a mid-bench
    # failure (the parity assert, an open_loop error) would otherwise
    # leak live worker/flusher threads into bench.py's subsequent TIMED
    # e2e phase, silently skewing the headline record. close() is
    # idempotent, so the success path's own closes are fine.
    fe = None
    try:
        # -- capacity: closed-loop per-request cost through the frontend --
        fe = ServeFrontend(
            kv, ServeConfig(replica="full", workers=2, max_queue_depth=4096)
        ).start()
        n_cal = 60 if smoke else 300
        for i in range(10):  # warm caches/queues
            fe.submit(PullRequest(keys=draw_keys(i, keys_per_req))).result(30)
        t0 = _time.perf_counter()
        for i in range(n_cal):
            fe.submit(PullRequest(keys=draw_keys(i, keys_per_req))).result(30)
        closed_loop_rate = n_cal / (_time.perf_counter() - t0)

        # -- offered-load points (open-loop, admission ON) --
        # the door admits ~0.6x the closed-loop calibration (the open-loop
        # harness itself costs CPU on this small host, so true service
        # capacity sits below the calibrated number) and bounds the backlog
        # at a depth whose drain time IS the p99 budget: p99 ≈ depth x
        # service_time, so the depth — not the arrival process — sets the
        # tail under overload
        admit_rate = max(50.0, 0.6 * closed_loop_rate)
        max_depth = 32 if smoke else 64
        duration = 1.0 if smoke else 2.5
        fe.close()
        fe = ServeFrontend(
            kv,
            ServeConfig(
                replica="full", workers=2,
                admission_rate=admit_rate, admission_burst=admit_rate / 10,
                max_queue_depth=max_depth,
            ),
        ).start()
        points = []
        for mult in (0.25, 3.0):
            points.append(
                open_loop_bench(
                    fe,
                    lambda i: PullRequest(keys=draw_keys(i, keys_per_req)),
                    rate=mult * closed_loop_rate,
                    duration_s=duration,
                    seed=int(mult * 10),
                    collectors=2,
                    warmup_requests=5,
                )
                | {"offered_multiple_of_capacity": mult, "admission": "on"}
            )
        fe.close()

        # -- the counterfactual: same overload, admission OFF (unbounded
        # queue; p99 grows with the backlog, i.e. with how long the
        # overload lasts — the collapse the door exists to prevent) --
        fe = ServeFrontend(
            kv, ServeConfig(replica="full", workers=2, max_queue_depth=0)
        ).start()
        no_adm = open_loop_bench(
            fe,
            lambda i: PullRequest(keys=draw_keys(i, keys_per_req)),
            rate=3.0 * closed_loop_rate,
            duration_s=duration,
            seed=30,
            collectors=2,
            warmup_requests=5,
        ) | {"offered_multiple_of_capacity": 3.0, "admission": "off"}
        fe.close()

        # -- coalescing: overlapping-key pulls on the live-table path --
        fe = ServeFrontend(
            kv,
            ServeConfig(
                replica="off", workers=8, coalesce_window_s=0.002,
                max_queue_depth=4096,
            ),
        ).start()
        n_co = 200 if smoke else 600
        tickets = [
            fe.submit(PullRequest(keys=draw_keys(i, keys_per_req)))
            for i in range(n_co)
        ]
        for t in tickets:
            t.result(60)
        co_stats = fe.stats()["coalescer"]
        # correctness spot-check rides along: coalesced rows == direct pull
        probe = draw_keys(3, keys_per_req)
        direct = kv.values(0, np.unique(probe))
        served = fe.submit(PullRequest(keys=np.unique(probe))).result(30)
        assert np.allclose(served, direct), "coalesced pull diverged"
        fe.close()

        # -- decode lane: speculative generation as served requests --
        import jax

        from ..models.speculative import speculative_generate
        from ..models.transformer import LMConfig, init_lm

        tcfg = LMConfig(vocab=64, d_model=32, n_heads=2, n_layers=2, d_ff=64)
        dcfg = LMConfig(vocab=64, d_model=16, n_heads=2, n_layers=1, d_ff=32)
        tparams = init_lm(jax.random.PRNGKey(0), tcfg)
        dparams = init_lm(jax.random.PRNGKey(1), dcfg)
        gamma = 4
        batch, prompt_len, steps = 2, 16, 8 if smoke else 16
        last_stats = {}

        def decode_fn(req: DecodeRequest):
            out, st = speculative_generate(
                tparams, tcfg, dparams, dcfg,
                jax.numpy.asarray(req.prompt), req.steps, gamma=gamma,
                return_stats=True,
            )
            last_stats["rounds"] = int(np.asarray(st["rounds"]))
            last_stats["accepted_frac"] = round(
                float(np.asarray(st["accepted_frac"])), 3
            )
            return out

        fe = ServeFrontend(
            kv, ServeConfig(replica="full", workers=1, max_queue_depth=64),
            decode_fn=decode_fn,
        ).start()
        rng = np.random.default_rng(11)

        def decode_req(i: int) -> DecodeRequest:
            return DecodeRequest(
                prompt=rng.integers(0, 64, (batch, prompt_len)).astype(np.int32),
                steps=steps,
            )

        t0 = _time.perf_counter()
        fe.submit(decode_req(0)).result(300)  # compile, excluded
        compile_s = _time.perf_counter() - t0
        n_dec = 2 if smoke else 4
        lat = []
        t0 = _time.perf_counter()
        for i in range(n_dec):
            tk = fe.submit(decode_req(1 + i))
            tk.result(300)
            lat.append(tk.latency_s())
        dec_wall = _time.perf_counter() - t0
        fe.close()

        return {
            "closed_loop_capacity_per_sec": round(closed_loop_rate, 1),
            "keys_per_request": keys_per_req,
            "points": points,
            "no_admission_overload": no_adm,
            # the acceptance ratio: overload p99 / low-load p99 with the
            # door on, vs the same ratio with it off
            "p99_overload_over_low_admitted": round(
                points[1]["latency_ms"]["p99_ms"]
                / max(1e-9, points[0]["latency_ms"]["p99_ms"]), 2,
            ),
            "p99_overload_over_low_unprotected": round(
                no_adm["latency_ms"]["p99_ms"]
                / max(1e-9, points[0]["latency_ms"]["p99_ms"]), 2,
            ),
            "coalesce": {
                "concurrent_requests": n_co,
                **co_stats,
            },
            "decode": {
                "model": "byte-LM random-init (wiring; trained evidence: "
                "BENCH_ONCHIP serve/spec_big)",
                "gamma": gamma,
                "batch": batch,
                "prompt_len": prompt_len,
                "steps": steps,
                "requests": n_dec,
                "compile_s": round(compile_s, 2),
                "tokens_per_sec": round(n_dec * batch * steps / dec_wall, 1),
                "latency_ms": {
                    "p50_ms": round(float(np.median(lat)) * 1e3, 1),
                    "max_ms": round(float(np.max(lat)) * 1e3, 1),
                },
                **last_stats,
            },
        }
    finally:
        if fe is not None:
            fe.close()


@benchmark("serve")
def serve_perf(smoke: bool = False) -> None:
    """Request-path serving SLO bench (see serve_ab). CPU-runnable:
    rates self-calibrate to the host; on-chip runs quote the same
    record shape with real device pulls."""
    out = serve_ab(smoke)
    low, over = out["points"]
    report(
        "serve_closed_loop_capacity",
        out["closed_loop_capacity_per_sec"], "requests/sec",
    )
    report("serve_p99_low_load", low["latency_ms"]["p99_ms"], "ms")
    report("serve_p99_overload_admitted", over["latency_ms"]["p99_ms"], "ms")
    report(
        "serve_p99_overload_unprotected",
        out["no_admission_overload"]["latency_ms"]["p99_ms"], "ms",
    )
    report("serve_goodput_overload", over["goodput_per_sec"], "requests/sec")
    report("serve_overload_shed_frac", over["shed_frac"], "fraction")
    report(
        "serve_coalesce_merge_factor",
        out["coalesce"]["requests"] / max(1, out["coalesce"]["submits"]),
        "requests/submit",
    )
    report(
        "serve_decode_tokens_per_sec",
        out["decode"]["tokens_per_sec"], "tokens/sec",
    )


def decode_batching_ab(smoke: bool = False) -> dict:
    """Continuous-batching decode A/B (serving/batcher.py): batched
    vs sequential tokens/s under join/leave churn, plus the
    device-resident replica serving a table LARGER than the host
    budget with zero degrades.

    Two sections, one dict (embedded by bench.py under
    ``decode_batching``):

    - **arms**: for each slot count B, the same session mix decoded
      two ways — sequentially (per-request ``speculative_generate``,
      the pre-batcher serving path: ONE fused while_loop per request)
      and through :class:`ContinuousBatcher` with wave admission
      (``admit_many``) and fused round blocks (``step_block``),
      sessions joining as slots free (join/leave churn, the serving
      arrival shape). Arms alternate back-to-back; the speedup quotes
      the MEDIAN of paired ratios (PR-3 bench discipline). TOKEN
      PARITY is asserted in-bench every rep: each session's batched
      stream must equal its own solo run.
    - **device_replica**: a :class:`ServeFrontend` with
      ``replica_device=True`` serving a weight table ~2x the
      configured host-replica budget (host mode refuses this loudly)
      through a live donated-push stream — the acceptance gate is
      ``degraded_served == 0`` across the refresh churn.

    ``gamma=2`` (not the batcher's default 4) because the A/B contrast
    is what this bench measures: sequential decode is weight-read
    bound, so the fewer tokens a round commits the more the batch
    amortizes each weight read. ``onchip_target`` states the bar the
    next device capture is judged against — this host is a SINGLE
    CPU core (no GEMM parallelism), where the measured roofline for
    batch-8 amortization sits near 3x and churn/join overhead lands
    the end-to-end median near 2.6x; the chip's bandwidth-bound
    batched matmuls are what the 3x bar describes.
    """
    import time as _time

    import jax

    from ..models.speculative import speculative_generate
    from ..models.transformer import LMConfig, init_lm
    from ..serving import (
        BatcherConfig,
        ContinuousBatcher,
        DecodeRequest,
        PullRequest,
        ServeConfig,
        ServeFrontend,
    )

    if smoke:
        tcfg = LMConfig(
            vocab=256, d_model=256, n_heads=4, n_layers=2, d_ff=512
        )
        dcfg = LMConfig(
            vocab=256, d_model=64, n_heads=2, n_layers=1, d_ff=128
        )
        arms, steps_mix, reps, sess_per_slot = (8,), (16, 24), 1, 2
    else:
        tcfg = LMConfig(
            vocab=256, d_model=512, n_heads=8, n_layers=2, d_ff=1024
        )
        dcfg = LMConfig(
            vocab=256, d_model=128, n_heads=2, n_layers=1, d_ff=256
        )
        arms, steps_mix, reps, sess_per_slot = (1, 4, 8, 16), (40, 48), 3, 6
    gamma = 2
    prompt_len = 8
    max_new = max(steps_mix)
    tparams = init_lm(jax.random.PRNGKey(0), tcfg)
    dparams = init_lm(jax.random.PRNGKey(1), dcfg)

    def mk_reqs(n: int, seed0: int = 0):
        rng = np.random.default_rng(seed0)
        return [
            DecodeRequest(
                prompt=rng.integers(
                    0, tcfg.vocab, (1, prompt_len)
                ).astype(np.int32),
                steps=steps_mix[i % len(steps_mix)],
            )
            for i in range(n)
        ]

    def run_seq(reqs):
        return [
            np.asarray(
                speculative_generate(
                    tparams, tcfg, dparams, dcfg,
                    jax.numpy.asarray(r.prompt), r.steps, gamma=gamma,
                )
            )
            for r in reqs
        ]

    def run_batched(b, reqs):
        # the churn harness: sessions join in waves as slots free,
        # finished sessions retire between (fused) rounds
        outs = {}
        pending = list(reqs)
        order = {id(r): i for i, r in enumerate(reqs)}
        for _ in range(100000):
            wave = []
            while pending and len(wave) < b.free_slots():
                wave.append((pending.pop(0), None))
            if wave:
                b.admit_many(wave)
            for h in b.step_block():
                outs[order[id(h.req)]] = h.out
            if not pending and b.active_sessions() == 0:
                return [outs[i] for i in range(len(reqs))]
        raise AssertionError("continuous batch failed to drain")

    arm_records = []
    for slots in arms:
        b = ContinuousBatcher(
            tparams, tcfg, dparams, dcfg,
            BatcherConfig(
                slots=slots, max_prompt=prompt_len, max_new=max_new,
                gamma=gamma, max_block=16,
            ),
        )
        t0 = _time.perf_counter()
        b.warmup()  # round + block + every pow2 join wave size
        run_seq(mk_reqs(len(steps_mix)))
        run_batched(b, mk_reqs(slots, seed0=999))
        compile_s = _time.perf_counter() - t0
        nsess = sess_per_slot * slots
        reqs = mk_reqs(nsess)
        total_toks = sum(r.steps for r in reqs)
        ratios, seq_tps, bat_tps = [], [], []
        for _ in range(reps):
            t0 = _time.perf_counter()
            seq_out = run_seq(reqs)
            t_seq = _time.perf_counter() - t0
            t0 = _time.perf_counter()
            bat_out = run_batched(b, reqs)
            t_bat = _time.perf_counter() - t0
            # the correctness contract, enforced inside the bench:
            # every session token-identical to its sequential run
            for s, c in zip(seq_out, bat_out):
                np.testing.assert_array_equal(s, c)
            ratios.append(t_seq / t_bat)
            seq_tps.append(total_toks / t_seq)
            bat_tps.append(total_toks / t_bat)
        st = b.stats()
        arm_records.append(
            {
                "slots": slots,
                "sessions": nsess,
                "tokens_per_session": sorted(set(steps_mix)),
                "compile_s": round(compile_s, 1),
                "seq_tokens_per_sec": round(float(np.median(seq_tps)), 1),
                "batched_tokens_per_sec": round(
                    float(np.median(bat_tps)), 1
                ),
                "speedup": round(float(np.median(ratios)), 2),
                "speedup_reps": [round(r, 2) for r in ratios],
                "accepted_frac": round(st["accepted_frac"], 3),
                "parity": "token-identical per session (asserted)",
            }
        )

    # -- device-resident replica over the host budget ------------------
    from ..parameter.kv_vector import KVVector

    mesh = _mesh()
    kv = KVVector(
        mesh=mesh, k=8, num_slots=1 << (10 if smoke else 14),
        hashed=True, name="serve_dev",
    )
    rng = np.random.default_rng(7)
    keys = np.unique(rng.integers(0, 1 << 20, 512))
    vals = rng.normal(size=(len(keys), 8)).astype(np.float32)
    kv.wait(kv.push(kv.request(channel=0), keys=keys, values=vals))
    table_bytes = int(kv.table(0).nbytes)
    budget = table_bytes // 2  # host replica mode refuses this table
    fe = ServeFrontend(
        kv,
        ServeConfig(
            replica="full", replica_device=True,
            replica_host_budget_bytes=budget, replica_refresh_s=0.02,
            workers=2, max_queue_depth=256,
        ),
    ).start()
    try:
        stop = _time.perf_counter() + (0.3 if smoke else 0.8)
        served = 0
        while _time.perf_counter() < stop:
            # pushes churn the table while reads ride the device
            # snapshot: every refresh consumes a consistent snapshot
            # of a donated-update stream
            kv.push(
                kv.request(channel=0), keys=keys[:64],
                values=rng.normal(size=(64, 8)).astype(np.float32),
            )
            fe.submit(
                PullRequest(keys=keys[rng.integers(0, len(keys), 16)])
            ).result(30)
            served += 1
        degraded = fe.degraded_served
        device_mode = bool(fe.stats()["replica"]["device"])
    finally:
        fe.close()

    by8 = next((a for a in arm_records if a["slots"] == 8), arm_records[-1])
    return {
        "model": {
            "target": "d512 2-layer byte-LM (random-init; self-"
            "agreeing draft => accepted_frac ~1.0)"
            if not smoke else "d256 2-layer byte-LM (smoke)",
            "draft": "d128 1-layer" if not smoke else "d64 1-layer",
            "gamma": gamma,
            "prompt_len": prompt_len,
        },
        "reps": reps,
        "arms": arm_records,
        "speedup_at_8": by8["speedup"],
        "device_replica": {
            "table_bytes": table_bytes,
            "host_budget_bytes": budget,
            "over_budget_factor": round(table_bytes / budget, 2),
            "refresh_s": 0.02,
            "requests_served": served,
            "degraded_served": int(degraded),
            "device": device_mode,
        },
        # the PR 8 pattern: the CPU record states the bar the next
        # reachable-device capture is judged against. This host is one
        # CPU core — batched GEMMs gain no parallelism and the batch-8
        # amortization roofline (weight reads + per-op dispatch over 8
        # rows) measures ~3x, of which churn/joins keep ~2.6x. On
        # chip the batched verify matmul is bandwidth-bound (weights
        # read once per round for the whole batch), which is what the
        # 3x bar describes.
        "onchip_target": {
            "decode_batched_speedup_at_8": ">= 3x sequential under "
            "join/leave churn (token parity asserted)",
            "measured_on": "next make bench-all with a reachable device",
        },
    }


@benchmark("decode_batching")
def decode_batching_perf(smoke: bool = False) -> None:
    """Continuous-batching decode A/B (see decode_batching_ab):
    batched-vs-sequential tokens/s with in-bench token parity, plus
    the device-replica-over-host-budget zero-degrade gate."""
    out = decode_batching_ab(smoke)
    by8 = next(
        (a for a in out["arms"] if a["slots"] == 8), out["arms"][-1]
    )
    report("decode_batched_speedup_at_8", out["speedup_at_8"], "x")
    report(
        "decode_batched_tokens_per_sec",
        by8["batched_tokens_per_sec"], "tokens/sec",
    )
    report(
        "decode_sequential_tokens_per_sec",
        by8["seq_tokens_per_sec"], "tokens/sec",
    )
    # served count MINUS degrades: positive only while the over-budget
    # device replica answers every request un-degraded (the report
    # contract wants values > 0; zero degrades is the gate, so quote
    # the clean-served count rather than the zero itself)
    dr = out["device_replica"]
    report(
        "decode_device_replica_clean_requests",
        dr["requests_served"] - dr["degraded_served"], "requests",
    )


@benchmark("trace")
def trace_perf(smoke: bool = False) -> None:
    """Capture a short synthetic run's flow-correlated timeline and
    export it as Chrome trace / Perfetto JSON (``make trace``).

    Drives the real pipeline pieces — an IngestPipeline (feeder +
    ordered prep pool) feeding executor steps submitted under each
    batch's flow id — with a JSONL span sink installed, then writes the
    merged timeline where ``PS_TRACE_OUT`` points (default
    ``<tmp>/ps_timeline_trace.json``; the raw JSONL lands next to it)
    and runs the critical-path analyzer over it. Open the export at
    https://ui.perfetto.dev — doc/OBSERVABILITY.md "Reading a timeline"
    walks what you see. Reported metrics double as liveness checks:
    zero captured events or uncorrelated flows would fail the registry
    smoke test."""
    import os
    import tempfile
    import time as _time

    from ..learner.ingest import IngestPipeline
    from ..system.executor import Executor
    from ..telemetry import attribution as attribution_mod
    from ..telemetry import spans as telemetry_spans
    from ..telemetry import timeline as timeline_mod

    out_path = os.environ.get("PS_TRACE_OUT") or os.path.join(
        tempfile.gettempdir(), "ps_timeline_trace.json"
    )
    jsonl_path = out_path + ".jsonl"
    try:
        os.remove(jsonl_path)  # fresh capture, never mix runs
    except OSError:
        pass
    n_batches = 6 if smoke else 24
    rng = np.random.default_rng(0)
    work = rng.random(1 << (12 if smoke else 16))

    def batches():
        for i in range(n_batches):
            yield i

    def prep(i):
        return float(np.sort(work).sum()) + i  # real CPU work

    prev = telemetry_spans.install_sink(telemetry_spans.JsonlSink(jsonl_path))
    t0 = _time.perf_counter()
    try:
        pipe = IngestPipeline(
            batches(), prep_fn=prep, workers=2, name="trace"
        ).start()
        ex = Executor(name="trace_bench", telemetry=True)
        for item in pipe:
            # the pipeline keeps the batch's flow active on this thread
            # until the next item, so the step correlates automatically
            ex.submit(lambda item=item: float(work[:1024].sum()) + item)
        ex.wait_all()
        ex.stop()
    finally:
        mine = telemetry_spans.install_sink(prev)
        if mine is not None and mine is not prev:
            mine.close()
    capture_s = _time.perf_counter() - t0

    events = timeline_mod.load_events(jsonl_path)
    trace = timeline_mod.to_chrome_trace(events)
    import json as _json

    with open(out_path, "w", encoding="utf-8") as f:
        _json.dump(trace, f)
    summary = attribution_mod.summarize(events)
    report("trace_events_captured", len(events), "events")
    report("trace_flows_correlated", summary["flows"].get("count", 0), "flows")
    report("trace_capture_events_per_sec", len(events) / capture_s, "events/sec")


@benchmark("bundle")
def bundle_probe(smoke: bool = False) -> None:
    """Capture a diagnostic bundle from a live mini-cluster and write
    it where ``PS_BUNDLE_OUT`` points (``make bundle``; default
    ``<tmp>/ps_bundle.json``) — the operator's "what was the system
    doing just now" artifact, identical in shape to what an alert
    firing or a shard death auto-captures (telemetry/blackbox.py).

    Drives the real pieces: the flight recorder armed as a tee (zero
    file IO), per-node recorders with metrics-delta samples, traced
    work under flow scopes, an AuxRuntime with two registered nodes —
    one of which goes SILENT before capture, so the bundle demonstrably
    carries staleness instead of a fabricated ring. The ``trace``
    section opens directly at https://ui.perfetto.dev."""
    import json as _json
    import os
    import tempfile
    import time as _time

    from ..system.aux_runtime import AuxRuntime
    from ..telemetry import blackbox
    from ..telemetry import spans as telemetry_spans

    out_path = os.environ.get("PS_BUNDLE_OUT") or os.path.join(
        tempfile.gettempdir(), "ps_bundle.json"
    )
    # targeted setup/cleanup like recovery_drill's (never a global
    # blackbox.reset(): that would disarm an enclosing run's tee, drop
    # its recorders, and clobber its rate-limit interval)
    prev_interval = blackbox.set_min_interval(0.0)
    was_armed = blackbox.installed_recorder() is not None
    aux = AuxRuntime(heartbeat_timeout=5.0, stale_after_s=0.08)
    try:
        aux.register("W0")
        aux.register("S0")
        blackbox.arm()
        for nid in ("W0", "S0"):
            blackbox.recorder(nid).clear()
            blackbox.recorder(nid).sample_metrics()
        # traced work: flows whose spans land in the ring
        n = 8 if smoke else 32
        work = np.random.default_rng(0).random(1 << 14)
        for i in range(n):
            with telemetry_spans.flow_scope(telemetry_spans.new_flow()):
                with telemetry_spans.span("bundle.demo", i=i):
                    float(np.sort(work).sum())
        for nid in ("W0", "S0"):
            blackbox.recorder(nid).sample_metrics()
        # S0 goes silent: only W0 keeps reporting past the staleness
        # window, so the capture must mark S0 stale (the honest half
        # of "ring dumps from every node")
        _time.sleep(0.1)
        aux.report_node("W0", wire=False)
        t0 = _time.perf_counter()
        bundle = aux.bundle(trigger="manual", force=True)
        capture_ms = (_time.perf_counter() - t0) * 1e3
        with open(out_path, "w", encoding="utf-8") as f:
            _json.dump(bundle, f, default=str)
        summary = blackbox.summarize_bundle(bundle)
        stale_nodes = [
            nid for nid, d in summary["nodes"].items() if d.get("stale")
        ]
        assert "S0" in stale_nodes, "silent node S0 not marked stale"
        assert summary["nodes"].get("W0", {}).get("events") is not None or (
            summary["nodes"].get("W0", {}).get("stale") is False
        ), "live node W0 has no ring dump"
        # no free-form print here: the benchmark runner's stdout is one
        # JSON line per metric (test_benchmarks parses every line); the
        # Makefile target echoes the output path for humans
        report("bundle_ring_nodes", len(summary["nodes"]), "nodes")
        report("bundle_stale_nodes", len(stale_nodes), "nodes")
        report("bundle_trace_events", summary["trace_events"], "events")
        report("bundle_capture_ms", capture_ms, "ms")
    finally:
        blackbox.set_min_interval(prev_interval)
        blackbox.drop_recorder("W0")
        blackbox.drop_recorder("S0")
        if not was_armed:
            blackbox.disarm()
        aux.stop()


def history_ab(smoke: bool = False) -> dict:
    """Steady-state overhead of the history plane (telemetry/history.py)
    priced the bench-discipline way: the SAME metric-churn workload
    (counter bumps, gauge sets, histogram observes, a periodic registry
    export — the report timer's read, which is exactly where the
    installed fold hook rides) with the HistoryStore installed vs
    absent, both orders inside one rep (on, off, off, on) so a monotone
    capacity drift on this flapping host cancels out of the paired
    ratio. The quoted claim is the MEDIAN ratio; because a seconds-scale
    capacity flap can still fake a stream ratio, the absolute per-fold
    cost is ALSO priced as a tight-loop ``fold_us_median`` over the
    full canonical instrument catalog. The A/B store runs at a 10 ms
    base resolution — two orders of magnitude HOTTER than the
    production 1 s cadence — so the quoted overhead is an upper bound,
    never a best case."""
    import time as _time

    from ..telemetry.history import HistoryStore
    from ..telemetry.instruments import install_all
    from ..telemetry.registry import MetricsRegistry

    n = 1500 if smoke else 6000
    reps = 3 if smoke else 5

    def build(with_history: bool):
        reg = MetricsRegistry()
        cs = [
            reg.counter(f"ab_hist_c{i}_total", "history A/B churn",
                        labelnames=("k",))
            for i in range(4)
        ]
        gs = [reg.gauge(f"ab_hist_g{i}", "history A/B churn")
              for i in range(4)]
        hist = reg.histogram(
            "ab_hist_lat_seconds", "history A/B churn",
            buckets=(1e-5, 1e-4, 1e-3, 1e-2),
        )
        if with_history:
            HistoryStore(reg, resolutions=((0.01, 600), (0.1, 720))).install()
        return reg, cs, gs, hist

    def run(world) -> None:
        reg, cs, gs, hist = world
        for i in range(n):
            cs[i & 3].labels(k=str(i & 7)).inc()
            gs[i & 3].set(float(i))
            hist.observe((i & 15) * 1e-4 + 1e-5)
            if i % 50 == 0:
                # the scrape/report read; with the store installed this
                # is what invokes the (rate-limited) fold hook
                reg.export_state()

    on, off = build(True), build(False)

    def timed(world) -> float:
        t0 = _time.perf_counter()
        run(world)
        return _time.perf_counter() - t0

    timed(on)  # warm both shapes
    timed(off)
    ratios, on_s, off_s = [], [], []
    for _ in range(reps):
        a1 = timed(on)
        o = (timed(off) + timed(off)) / 2
        a2 = timed(on)
        ratios.append(((a1 + a2) / 2) / max(o, 1e-9))
        on_s.append((a1 + a2) / 2)
        off_s.append(o)
    ratios.sort()
    on_s.sort()
    off_s.sort()

    # tight-loop absolute: one forced fold over the FULL canonical
    # catalog (every instrument family, one live series each) — the
    # pure per-fold cost no workload flap can fake
    cat_reg = MetricsRegistry()
    instruments = install_all(cat_reg)
    for inst in instruments.values():
        target = (
            inst.labels(**{ln: "probe" for ln in inst.labelnames})
            if inst.labelnames else inst
        )
        if inst.kind == "histogram":
            target.observe(0.001)
        elif inst.kind == "gauge":
            target.set(1.0)
        else:
            target.inc()
    store = HistoryStore(cat_reg)
    m = 50 if smoke else 200
    folds = []
    for _ in range(m):
        t0 = _time.perf_counter()
        store.fold(force=True)
        folds.append(_time.perf_counter() - t0)
    folds.sort()
    snap = store.snapshot()
    return {
        "reps": reps,
        "steps_per_rep": n,
        "ratio_median": round(ratios[len(ratios) // 2], 3),
        "on_ms_median": round(on_s[len(on_s) // 2] * 1e3, 3),
        "off_ms_median": round(off_s[len(off_s) // 2] * 1e3, 3),
        "fold_us_median": round(folds[len(folds) // 2] * 1e6, 1),
        "fold_series": snap["series"],
        "resolutions": snap["resolutions"],
    }


@benchmark("history_ab")
def history_ab_perf(smoke: bool = False) -> None:
    """History-plane overhead A/B (see history_ab): metric-churn
    workload with the ring-cascade fold hook installed vs absent,
    paired-median ratio + tight-loop per-fold cost over the full
    instrument catalog."""
    out = history_ab(smoke)
    report("history_overhead_ratio_median", out["ratio_median"], "x")
    report("history_fold_us_median", out["fold_us_median"], "us")
    report("history_fold_series", out["fold_series"], "series")


def _drill_batch(seed: int, i: int, key_space: int, n: int, k: int):
    """Deterministic training batch ``i`` — regenerable by index, which
    is what lets the recovery handler REPLAY acked-but-unbacked updates
    instead of journaling arrays (doc/ROBUSTNESS.md "The drill")."""
    rng = np.random.default_rng((seed << 20) + i)
    keys = rng.integers(0, key_space, n).astype(np.int64)
    vals = rng.normal(size=(n, k)).astype(np.float32)
    return keys, vals


def _learning_mesh():
    """A mesh with >= 2 server shards when the host has the devices —
    the learning probe's shard-balance evidence needs real per-shard
    key ranges, not a single-shard triviality."""
    import jax

    from ..system.postoffice import Postoffice

    Postoffice.reset()
    n = len(jax.devices())
    if n >= 2:
        return Postoffice.instance().start(
            num_data=n // 2, num_server=2
        ).mesh
    return Postoffice.instance().start().mesh


def _divergence_drill(mesh, smoke: bool = False) -> dict:
    """Seeded divergence drill: an LR blow-up (square loss, alpha 1e12)
    NaNs the trajectory within a few steps; the learning plane judges
    the collected steps divergent (``ps_learning_divergence_total``),
    the SHIPPED ``loss_divergence`` rule walks inactive → pending →
    firing, and the firing transition captures a flight-recorder
    diagnostic bundle through the PR 13 alert trigger plane — the same
    listener wiring ``AuxRuntime.set_alerts`` installs. Deterministic
    under a fake clock; all tier-1-tested (tests/test_learning.py)."""
    from ..apps.linear.async_sgd import AsyncSGDWorker
    from ..apps.linear.config import (
        Config,
        LearningRateConfig,
        LossConfig,
        PenaltyConfig,
        SGDConfig,
    )
    from ..telemetry import alerts as alerts_mod
    from ..telemetry import blackbox
    from ..telemetry import learning as learning_mod
    from ..utils.sparse import random_sparse

    rule = next(
        r for r in alerts_mod.default_rules() if r.name == "loss_divergence"
    )
    clock = [0.0]
    mgr = alerts_mod.AlertManager([rule], clock=lambda: clock[0])
    prev_interval = blackbox.set_min_interval(0.0)
    was_armed = blackbox.installed_recorder() is not None
    blackbox.arm()
    bundles: list = []

    def on_transition(ev) -> None:
        # the AuxRuntime._maybe_bundle_on_alert wiring, drill-local:
        # a firing alert captures the evidence while it is in the ring
        if ev.to == "firing" and ev.rule == "loss_divergence":
            b = blackbox.trigger_bundle("alert", detail=ev.rule)
            if b is not None:
                bundles.append(b)

    mgr.add_listener(on_transition)
    conf = Config()
    conf.loss = LossConfig(type="square")
    conf.penalty = PenaltyConfig(type="l2", lambda_=[0.0])
    # the blow-up: plain SGD at a constant learning rate orders of
    # magnitude past stability turns the square loss's w-proportional
    # gradient into an exponential — float32 overflows to Inf/NaN
    # within a handful of steps on any data (FTRL would self-damp via
    # its adaptive per-coordinate rate, which is exactly why the drill
    # picks the updater the reference's SGDEntry models)
    conf.learning_rate = LearningRateConfig(
        type="constant", alpha=1e10, beta=1.0
    )
    conf.async_sgd = SGDConfig(
        algo="standard", minibatch=64, num_slots=1 << 9, max_delay=0,
    )
    worker = AsyncSGDWorker(conf, mesh=mesh, name="learning_diverge")
    states = []
    try:
        mgr.evaluate()  # t=0 baseline sample — a rate needs a window
        states.append(mgr.states()[rule.name].state_name)
        n_steps = 8 if smoke else 12
        for i in range(n_steps):
            b = random_sparse(64, 1 << 12, 6, seed=100 + i, binary=True)
            b.y = np.where(np.arange(64) % 2 == 0, 1.0, -1.0).astype(
                np.float32
            )
            ts = worker._submit_prepped(
                worker.prep(b, device_put=False), with_aux=False
            )
            worker.collect(ts)
        plane = learning_mod.get_plane("learning_diverge")
        divergences = dict(plane.snapshot()["divergence"]) if plane else {}
        clock[0] = 5.0
        mgr.evaluate()  # pending → firing in one tick (for_s=0)
        states.append(mgr.states()[rule.name].state_name)
        fired = rule.name in mgr.firing()
        # traffic stops; the window slides past the burst → resolved
        clock[0] = 5.0 + rule.window_s + 10.0
        mgr.evaluate()
        states.append(mgr.states()[rule.name].state_name)
    finally:
        worker.executor.stop()
        blackbox.set_min_interval(prev_interval)
        if not was_armed:
            blackbox.disarm()
    return {
        "divergence_counts": divergences,
        "states_seen": states,
        "fired": bool(fired),
        "resolved": states[-1] in ("resolved", "inactive"),
        "bundle_captured": bool(bundles),
        "bundle_trigger": (
            dict(bundles[0]["trigger"]) if bundles else None
        ),
    }


def learning_truth(smoke: bool = False) -> dict:
    """The learning truth plane probe (telemetry/learning.py), embedded
    under ``learning`` in every bench record and run standalone via
    ``make learning-bench``.

    A short real training run through the collect path on a bounded-
    delay config (τ=3) yields: the REALIZED staleness histogram with
    the in-record bound verdict (``within_bound``: observed max <= the
    configured ``SGDConfig.max_delay`` — the OSDI'14 contract as a
    measured invariant), per-server-shard key-heat load shares + the
    imbalance ratio + the top-k hot-slot table, the loss/grad-norm
    convergence trajectory from the step builders' in-jit side outputs,
    and a sketch-vs-exact heat parity check (the windowed count-min
    against exact slot counts over the same stream). A seeded LR
    blow-up then drives the shipped ``loss_divergence`` rule to firing
    with a diagnostic bundle attached. Record METADATA, never banded by
    the bench-diff sentinel (script/bench_diff.py METADATA_SECTIONS)."""
    from ..apps.linear.async_sgd import AsyncSGDWorker
    from ..apps.linear.config import (
        Config,
        LearningRateConfig,
        PenaltyConfig,
        SGDConfig,
    )
    from ..parallel import mesh as meshlib
    from ..telemetry import learning as learning_mod
    from ..utils.sparse import random_sparse

    mesh = _learning_mesh()
    tau = 3
    minibatch = 128
    n_batches = 24 if smoke else 48
    conf = Config()
    conf.penalty = PenaltyConfig(type="l1", lambda_=[0.1])
    conf.learning_rate = LearningRateConfig(
        type="decay", alpha=0.1, beta=1.0
    )
    conf.async_sgd = SGDConfig(
        algo="ftrl", minibatch=minibatch, num_slots=1 << 10, max_delay=tau,
    )
    worker = AsyncSGDWorker(conf, mesh=mesh, name="learning_probe")

    def batch(i: int):
        b = random_sparse(minibatch, 1 << 16, 8, seed=i, binary=True)
        b.y = np.where(
            (b.indices.reshape(minibatch, -1) % 64 < 16).mean(1) > 0.24,
            1.0, -1.0,
        ).astype(np.float32)
        return b

    batches = [batch(i) for i in range(n_batches)]
    # exact heat ground truth over the SAME stream, hashed through the
    # SAME directory the sketch sees
    exact = np.zeros(worker.num_slots, np.int64)
    for b in batches:
        np.add.at(
            exact, worker.directory.slots(np.asarray(b.indices)), 1
        )
    try:
        worker.train(iter(batches))
        plane = learning_mod.get_plane("learning_probe")
        snap = plane.snapshot()
        uniq = np.flatnonzero(exact)
        est = plane.heat.estimate(uniq)
        # no decay window elapses on a run this short, so CM semantics
        # apply directly: estimates are exact up to hash collisions
        # (upper-biased, never under)
        parity = {
            "distinct_slots": int(uniq.size),
            "exact_match_frac": round(float(np.mean(est == exact[uniq])), 4),
            "upper_bound_frac": round(float(np.mean(est >= exact[uniq])), 4),
        }
    finally:
        worker.executor.stop()
    return {
        "config": {
            "max_delay": tau,
            "n_batches": n_batches,
            "minibatch": minibatch,
            "num_slots": worker.num_slots,
            "num_shards": meshlib.num_servers(mesh),
        },
        "staleness": snap["staleness"],
        "shards": snap["shards"],
        "hot_slots": snap["hot_slots"][:8],
        "examples": snap["examples"],
        "collected_steps": snap["collected_steps"],
        "trajectory_tail": snap["trajectory_tail"][-8:],
        "heat_parity": parity,
        "divergence_drill": _divergence_drill(mesh, smoke),
    }


@benchmark("learning")
def learning_perf(smoke: bool = False) -> None:
    """The learning truth plane headline (``make learning-bench``):
    realized staleness must respect the configured τ, the sketch must
    agree with exact heat on a small run, shard shares must cover the
    traffic, the convergence trajectory must be finite on a healthy
    run — and the seeded divergence drill must fire the shipped rule
    with a bundle attached."""
    out = learning_truth(smoke)
    st = out["staleness"]
    assert st["within_bound"], (
        f"realized staleness {st['observed_max']} breached the "
        f"configured tau {st['configured_tau']}"
    )
    assert out["heat_parity"]["upper_bound_frac"] == 1.0, out["heat_parity"]
    drill = out["divergence_drill"]
    assert drill["fired"] and drill["bundle_captured"], drill
    # the >0 report contract forbids printing a raw observed_max that
    # can legitimately be 0 (an always-snapshotting run) — the honest
    # headline is the verdict, asserted above, with the raw value in
    # the record's learning.probe.staleness section
    report("learning_staleness_within_bound", 1.0, "bool")
    report("learning_staleness_submits", st["submits"], "submissions")
    report(
        "learning_heat_exact_match",
        out["heat_parity"]["exact_match_frac"],
        "fraction",
    )
    report(
        "learning_shard_imbalance",
        out["shards"]["imbalance"] or 0.0,
        "max_over_mean",
    )
    report("learning_examples_confirmed", out["examples"], "examples")


def recovery_drill(smoke: bool = False) -> dict:
    """Kill-one-shard recovery drill under concurrent train + serve load
    (doc/ROBUSTNESS.md — ROADMAP item 2's acceptance drill, embedded in
    every bench record under ``recovery``).

    The script, all under live load (a paced training push stream and a
    closed-loop serving client against the SAME store):

    1. **healthy** — periodic consistent replica backups
       (``ReplicaManager.start_periodic`` → snapshot steps THROUGH the
       store executor, so donated pushes can't tear them) while the
       trainer acks pushes and serving reads live.
    2. **kill** — the backup stream stops, then ``S0`` dies the way real
       shards die: its heartbeats stop arriving (injected
       ``heartbeat.report`` silence), its table is wiped (the
       replacement starts empty), and the serving store path starts
       failing (``serve.pull`` / ``serve.refresh`` faults). Serving
       DEGRADES to the stale read replica (503-distinct accounting)
       instead of erroring; training keeps acking into the void —
       exactly the updates the replay contract must not lose.
    3. **detect + recover** — the RecoveryCoordinator's poll declares
       S0 dead after the heartbeat timeout; the server-death handler
       parks the trainer (bounded-delay semantics: survivors stop
       pushing while the shard recovers), installs the last consistent
       snapshot through the executor, REPLAYS every acked push past the
       snapshot's barrier timestamp in original order, then re-arms the
       store path and resumes.
    4. **verify** — after the stream completes, the drilled table must
       be BIT-identical to an undisturbed run of the same batch
       sequence: zero lost *acknowledged* updates, to the bit.

    Also measured: detection / recovery / MTTR wall times, serve
    requests completed/degraded/shed/failed, and the disarmed-overhead
    paired check (fault points present-but-disarmed vs stripped) that
    keeps the "zero overhead when disarmed" claim honest.
    """
    import threading
    import time as _time

    import jax
    import jax.numpy as jnp

    from ..parallel import mesh as meshlib
    from ..parameter.kv_vector import KVVector
    from ..parameter.replica import ReplicaManager
    from ..serving import (
        DegradedError,
        PullRequest,
        RejectedError,
        ServeConfig,
        ServeFrontend,
    )
    from ..system import faults
    from ..system.heartbeat import HeartbeatCollector, HeartbeatReport
    from ..system.recovery import RecoveryCoordinator

    mesh = _mesh()
    seed = 7
    k = 4
    num_slots = 1 << (10 if smoke else 12)
    key_space = 1 << 16
    n_per_batch = 64
    # the stream must OUTLIVE detection in every mode: the drill's
    # whole point is recovery under live load, so the trainer has to
    # still be pushing when the handler parks it. Post-kill batches x
    # (>=4ms pacing) must exceed hb_timeout + poll + margin — with
    # 100+ post-kill batches at >=4ms the park is guaranteed even in
    # smoke (the record's trainer_parked field pins it in CI).
    n_batches = 120 if smoke else 240
    kill_at = n_batches // 6
    hb_timeout = 0.3

    def batch(i: int):
        return _drill_batch(seed, i, key_space, n_per_batch, k)

    def push_and_ack(kv, i: int) -> int:
        keys, vals = batch(i)
        ts = kv.push(kv.request(channel=0), keys=keys, values=vals)
        kv.executor.wait(ts, timeout=60)
        return ts

    # -- the undisturbed reference trajectory (also warms every jit:
    # push scatter-add, gather, snapshot copy — so compile stalls can't
    # eat the drill's heartbeat margin) --
    kv_ref = KVVector(
        mesh=mesh, k=k, num_slots=num_slots, hashed=True, name="drill_ref"
    )
    for i in range(n_batches):
        push_and_ack(kv_ref, i)
    t_ref = np.array(kv_ref.table(0, copy=True))
    kv_ref.executor.stop()

    # -- the drilled store + chaos-plane wiring --
    faults.reset()
    # flight recorder (telemetry/blackbox.py): armed for the whole
    # drill so the shard death auto-captures a diagnostic bundle with
    # the pre-death evidence still in the rings. Per-node recorders for
    # the drill's logical nodes; min capture interval dropped so the
    # death trigger is never rate-limit-suppressed by an earlier
    # capture. The bench sink is parked around the drill
    # (attach_recovery) — the tee records into memory only. Cleanup is
    # TARGETED, not a global reset: the drill restores exactly the
    # state it touched (its recorders, the interval, its tee), so an
    # enclosing bench run's bundle deque — which attach_blackbox
    # discloses as bundles_captured — survives the drill.
    from ..telemetry import alerts as alerts_mod
    from ..telemetry import blackbox
    from ..telemetry import registry as telemetry_registry

    prev_min_interval = blackbox.set_min_interval(0.0)
    was_armed = blackbox.installed_recorder() is not None
    blackbox.arm()
    blackbox.recorder("W0").clear()  # a prior drill in this process
    blackbox.recorder("S0").clear()  # must not leak into this bundle
    node_alerts = None
    if telemetry_registry.enabled():
        node_alerts = alerts_mod.AlertManager(
            [r for r in alerts_mod.default_rules()
             if r.name == "node_deaths"]
        )
        node_alerts.evaluate()  # baseline sample: rate needs a window
    # independently-metered update accounting (the learning truth
    # plane's progress side): baseline the parameter plane's push-key
    # counter for the drilled store BEFORE it exists, so the post-drill
    # delta is exactly this drill's pushed keys
    push_tel = None
    push_keys0 = 0.0
    if telemetry_registry.enabled():
        from ..telemetry.instruments import parameter_instruments

        push_tel = parameter_instruments(
            telemetry_registry.default_registry()
        )["push_keys"]
        push_keys0 = push_tel.value(store="drill_live", channel=0)
    kv = KVVector(
        mesh=mesh, k=k, num_slots=num_slots, hashed=True, name="drill_live"
    )
    rm = ReplicaManager()
    rm.backup_consistent(kv)  # a snapshot exists before any fault can
    rm.start_periodic(kv, interval_s=0.04)

    collector = HeartbeatCollector(timeout=hb_timeout)
    rc = RecoveryCoordinator(collector, handler_retry=None)  # replay is
    # not idempotent: a partial replay retried would double-apply, so
    # the drill's handler runs exactly once and fails loudly instead

    fe = ServeFrontend(
        kv,
        ServeConfig(
            replica="fallback",  # live-first reads; replica = degraded path
            replica_refresh_s=0.15,
            live_pull_deadline_s=2.0,
            degraded_max_staleness_s=60.0,
            workers=2,
            max_queue_depth=256,
        ),
    ).start()
    rng = np.random.default_rng(seed + 1)
    u = rng.random((128, 16))
    pool = (u * u * u * key_space).astype(np.int64)  # hot-headed draws
    fe.submit(PullRequest(keys=pool[0])).result(30)  # warm the pull lane

    counts = {"ok": 0, "shed": 0, "failed": 0}  # serve-thread-only writes
    stop_serve = threading.Event()

    def serve_loop() -> None:
        i = 0
        while not stop_serve.is_set():
            try:
                fe.submit(PullRequest(keys=pool[i % len(pool)])).result(10)
                counts["ok"] += 1
            except RejectedError:
                counts["shed"] += 1
            except Exception:  # DegradedError and organic failures both
                counts["failed"] += 1  # count here; degraded SUCCESSES
                # are counted by the frontend (degraded_served)
            i += 1
            _time.sleep(0.002)

    acked: list = []  # (push ts, batch index); guarded-by: ack_lock
    ack_lock = threading.Lock()
    pause_req = threading.Event()
    parked = threading.Event()
    train_err: list = []

    def trainer() -> None:
        try:
            for i in range(n_batches):
                if pause_req.is_set():
                    parked.set()
                    while pause_req.is_set():
                        _time.sleep(0.002)
                    parked.clear()
                ts = push_and_ack(kv, i)
                with ack_lock:
                    acked.append((ts, i))
                _time.sleep(0.004)  # paced: a continuous live stream,
                # not a burst that outruns the detection window
        except BaseException as e:  # surfaced after join
            train_err.append(e)

    stop_beat = threading.Event()

    def beater() -> None:
        beats = 0
        while not stop_beat.wait(0.04):
            collector.report("S0", HeartbeatReport(hostname="S0"))
            collector.report("W0", HeartbeatReport(hostname="W0"))
            beats += 1
            if beats % 3 == 0:
                # periodic metrics-delta samples into the survivors'
                # flight-recorder rings (the report-timer cadence —
                # what a bundle's per-node metrics history is made of)
                for nid in ("W0", "S0"):
                    rec = blackbox.recorder(nid, create=False)
                    if rec is not None:
                        rec.sample_metrics()

    t_kill = [0.0]
    t_detect = [0.0]
    t_recovered = [0.0]
    replayed = [0]
    barrier_used = [-1]
    trainer_parked = [False]

    trainer_t = threading.Thread(target=trainer, name="drill-trainer")

    def on_server_dead(nid: str) -> None:
        if t_kill[0] == 0.0:
            # a loaded host can stall the beater past the heartbeat
            # timeout BEFORE the drill killed anything — that is a
            # false positive, and consuming the exactly-once handler
            # on it would mask the real kill. Revive and keep watching.
            rc.revive(nid)
            return
        t_detect[0] = _time.perf_counter()
        # bounded-delay semantics: survivors stop pushing while the
        # shard recovers (park the trainer between batches)
        pause_req.set()
        while not parked.is_set() and trainer_t.is_alive():
            _time.sleep(0.002)
        # the under-live-load property CI pins: the trainer was ALIVE
        # and parked (not already finished) when recovery began
        trainer_parked[0] = parked.is_set()
        rec_ok = rm.recover(kv, through_executor=True)
        assert rec_ok, "no replica snapshot to recover from"
        barrier = rm.barrier(kv.name).get(0, -1)
        barrier_used[0] = barrier
        with ack_lock:
            replay = [(ts, i) for ts, i in acked if ts > barrier]
        for _, i in replay:  # original order — FP addition must re-run
            push_and_ack(kv, i)  # in the exact sequence it first ran
        replayed[0] = len(replay)
        # the replacement shard is up: store path + heartbeats return
        faults.disarm("serve.pull")
        faults.disarm("serve.refresh")
        faults.disarm("heartbeat.report")
        t_recovered[0] = _time.perf_counter()
        pause_req.clear()

    rc.on_server_dead(on_server_dead)
    collector.report("S0", HeartbeatReport(hostname="S0"))
    collector.report("W0", HeartbeatReport(hostname="W0"))

    serve_t = threading.Thread(target=serve_loop, name="drill-serve")
    beat_t = threading.Thread(target=beater, name="drill-beater")
    degraded_probes = 0
    try:
        beat_t.start()
        rc.start(interval=0.03)
        trainer_t.start()
        serve_t.start()

        # phase 1 (healthy): run until the kill point has been ACKED
        while True:
            with ack_lock:
                n_acked = len(acked)
            if n_acked >= kill_at or train_err:
                break
            _time.sleep(0.005)
        if train_err:
            raise train_err[0]

        # phase 2 (kill): the dead shard's backup stream stops FIRST —
        # a crashed node cannot keep snapshotting — then make sure at
        # least one acked update postdates the final barrier (the
        # replay set must be provably non-empty)
        rm.stop_periodic()
        barrier_before = rm.barrier(kv.name).get(0, -1)
        replay_deadline = _time.perf_counter() + 30
        while True:
            with ack_lock:
                if any(ts > barrier_before for ts, _ in acked):
                    break
            assert trainer_t.is_alive() and (
                _time.perf_counter() < replay_deadline
            ), "no acked update ever postdated the final backup barrier"
            _time.sleep(0.002)
        faults.arm("heartbeat.report", kind="silence", match="S0")
        faults.arm("serve.pull", kind="raise")
        faults.arm("serve.refresh", kind="raise")
        t_kill[0] = _time.perf_counter()
        # wipe the shard through the executor (the replacement starts
        # empty; the submitted step serializes with in-flight pushes)
        zeros = jax.device_put(
            jnp.zeros((kv.num_slots, kv.k), kv.dtype),
            meshlib.table_sharding(kv.mesh),
        )
        kv.executor.wait(
            kv.submit(lambda: kv.set_table(0, zeros), kv.request(channel=0)),
            timeout=60,
        )
        # deterministic degraded evidence: requests in the dead window
        # must be ANSWERED (stale) — the 503-vs-429 story, measured
        for j in range(3):
            try:
                fe.submit(PullRequest(keys=pool[j])).result(10)
                degraded_probes += 1
            except Exception:
                pass

        # phase 3: detection + recovery run on the coordinator thread;
        # phase 4: the trainer finishes the stream
        deadline = _time.perf_counter() + 90
        while t_recovered[0] == 0.0 and _time.perf_counter() < deadline:
            if node_alerts is not None:
                node_alerts.evaluate()
            _time.sleep(0.005)
        assert t_recovered[0] > 0.0, "recovery never completed"
        # the node_deaths rule sees the coordinator's deaths counter
        # tick and walks pending->firing (for_s=0: one evaluation)
        if node_alerts is not None:
            alert_deadline = _time.perf_counter() + 10
            while (
                "node_deaths" not in node_alerts.firing()
                and _time.perf_counter() < alert_deadline
            ):
                node_alerts.evaluate()
                _time.sleep(0.01)
        trainer_t.join(timeout=120)
        assert not trainer_t.is_alive(), "trainer wedged"
        if train_err:
            raise train_err[0]
    finally:
        try:
            faults.reset()
            rm.stop_periodic()
            stop_serve.set()
            stop_beat.set()
            rc.stop()
            for t in (serve_t, beat_t, trainer_t):
                if t.ident is not None:
                    t.join(timeout=60)
            fe.close()
        finally:
            # grab the death's bundle BY TRIGGER KIND — last_bundle()
            # could be a later capture (a straggling DegradedError from
            # the dead window fires the degraded trigger with the
            # interval still 0) whose rings carry no staleness override
            # for S0
            death_bundle = next(
                (b for b in reversed(blackbox.bundles())
                 if b["trigger"]["kind"] == "node_death"),
                None,
            )
            # targeted cleanup (never a global reset — see the arm
            # comment): the rate-limit override, the drill's per-node
            # recorders, and the drill's tee (only if the drill armed
            # it) must not leak past the drill even when it raises —
            # its OWN nested finally, so a failing teardown step above
            # (a wedged join, a close error) cannot skip it
            blackbox.set_min_interval(prev_min_interval)
            blackbox.drop_recorder("W0")
            blackbox.drop_recorder("S0")
            if not was_armed:
                blackbox.disarm()

    kv.executor.wait_all(pop=False, timeout=60)
    t_drill = np.array(kv.table(0, copy=True))
    fe_stats = fe.stats()
    kv.executor.stop()
    # the shard death's auto-captured diagnostic bundle (the
    # RecoveryCoordinator's node_death trigger): summarized into the
    # record under ``blackbox`` — drill METADATA the bench-diff
    # sentinel never bands (script/bench_diff.py METADATA_SECTIONS)
    blackbox_section: dict = {"captured": death_bundle is not None}
    if death_bundle is not None:
        blackbox_section = blackbox.summarize_bundle(death_bundle)
    if node_alerts is not None:
        st = node_alerts.states().get("node_deaths")
        blackbox_section["node_deaths_alert"] = (
            st.state_name if st is not None else "absent"
        )
    bit_identical = (
        t_ref.dtype == t_drill.dtype
        and t_ref.shape == t_drill.shape
        and t_ref.tobytes() == t_drill.tobytes()
    )
    # the bit-identity claim, independently METERED (PR 15): every key
    # the trainer acked plus every key the handler replayed must show
    # in the parameter plane's own push-key counter for this store —
    # a replay that silently lost (or double-ran) updates would still
    # reconcile bit-identically on idempotent data, but it cannot fool
    # a counter the push path ticks per request
    update_accounting = None
    if push_tel is not None:
        pushed = int(
            push_tel.value(store="drill_live", channel=0) - push_keys0
        )
        expected = (n_batches + replayed[0]) * n_per_batch
        update_accounting = {
            "pushed_keys_metered": pushed,
            "expected_keys": expected,
            "acked_updates": n_batches,
            "replayed_updates": replayed[0],
            "keys_per_batch": n_per_batch,
            "metered_matches": pushed == expected,
        }
        assert update_accounting["metered_matches"], update_accounting

    # -- disarmed-overhead paired check: the SAME push stream with the
    # fault points live-but-disarmed vs check() stubbed out (the
    # no-call-sites counterfactual), back-to-back per rep, median of
    # paired ratios (ROADMAP bench discipline) --
    kv2 = KVVector(
        mesh=mesh, k=k, num_slots=1 << 10, hashed=True, name="drill_ovh"
    )
    okeys, ovals = batch(0)

    def ovh_stream(m: int = 24) -> None:
        for _ in range(m):
            kv2.executor.wait(
                kv2.push(kv2.request(channel=0), keys=okeys, values=ovals)
            )

    ovh_stream()  # warm
    real_check = faults.check
    ratios = []
    reps = 3 if smoke else 5
    for _ in range(reps):
        # both orders inside one rep (disarmed, stripped, stripped,
        # disarmed) so a monotone capacity drift on this flapping host
        # cancels out of the paired ratio instead of biasing it
        t0 = _time.perf_counter()
        ovh_stream()
        disarmed_s = _time.perf_counter() - t0
        faults.check = lambda point, detail=None: None  # stripped arm
        try:
            t0 = _time.perf_counter()
            ovh_stream()
            ovh_stream()
            stripped_s = (_time.perf_counter() - t0) / 2
        finally:
            faults.check = real_check
        t0 = _time.perf_counter()
        ovh_stream()
        disarmed_s = (disarmed_s + (_time.perf_counter() - t0)) / 2
        ratios.append(disarmed_s / max(stripped_s, 1e-9))
    kv2.executor.stop()
    # the stream ratio is hostage to this host's seconds-scale capacity
    # flap (ROADMAP bench discipline), so ALSO time the disarmed check
    # itself — a tight-loop ns/call that a flap cannot fake. This is
    # the per-step cost every fault point adds when nothing is armed.
    n_calls = 200_000
    t0 = _time.perf_counter()
    for _ in range(n_calls):
        faults.check("executor.step")
    check_ns = (_time.perf_counter() - t0) / n_calls * 1e9

    return {
        "config": {
            "n_batches": n_batches,
            "kill_at_batch": kill_at,
            "keys_per_batch": n_per_batch,
            "k": k,
            "num_slots": num_slots,
            "backup_interval_s": 0.04,
            "heartbeat_timeout_s": hb_timeout,
        },
        "detection_ms": round((t_detect[0] - t_kill[0]) * 1e3, 1),
        "recovery_ms": round((t_recovered[0] - t_detect[0]) * 1e3, 1),
        "mttr_ms": round((t_recovered[0] - t_kill[0]) * 1e3, 1),
        "replayed_updates": replayed[0],
        "acked_updates": n_batches,
        "barrier_ts": barrier_used[0],
        "backup_version_used": (rm.meta(kv.name) or {}).get("version"),
        "trainer_parked": trainer_parked[0],
        "trajectory_bit_identical": bool(bit_identical),
        "update_accounting": update_accounting,
        "blackbox": blackbox_section,
        "serve": {
            "requests": counts["ok"] + counts["shed"] + counts["failed"],
            "completed_ok": counts["ok"],
            "degraded_served": fe_stats["degraded_served"],
            "degraded_probes_in_dead_window": degraded_probes,
            "shed": counts["shed"],
            "failed": counts["failed"],
        },
        "disarmed_overhead": {
            "reps": reps,
            "ratio_median": round(float(np.median(ratios)), 3),
            "check_ns_per_call": round(check_ns, 1),
        },
    }


@benchmark("recovery_drill")
def recovery_drill_perf(smoke: bool = False) -> None:
    """The chaos-plane headline (``make chaos-bench``): injected shard
    death under live train+serve load must be detected, degraded
    around, and recovered with zero lost acknowledged updates — the
    post-drill table bit-identical to an undisturbed run. Reported
    times are this host's; the same drill shape runs on chip."""
    out = recovery_drill(smoke)
    assert out["trajectory_bit_identical"], (
        "post-recovery trajectory diverged from the undisturbed run — "
        "acknowledged updates were lost"
    )
    assert out["replayed_updates"] > 0, (
        "drill proved nothing: no acked update postdated the barrier"
    )
    assert out["trainer_parked"], (
        "drill proved nothing: the trainer finished before detection, "
        "so recovery never ran against live load — size n_batches/"
        "pacing so the stream outlives the heartbeat timeout"
    )
    bb = out["blackbox"]
    assert bb.get("captured"), (
        "shard death did not auto-capture a diagnostic bundle"
    )
    assert bb["nodes"].get("S0", {}).get("stale"), (
        "dead shard S0 is not marked stale in the bundle"
    )
    assert not bb["nodes"].get("W0", {}).get("stale", True), (
        "surviving node W0's ring dump is missing from the bundle"
    )
    assert bb.get("node_deaths_alert", "firing") == "firing", (
        "node_deaths alert never reached firing during the drill"
    )
    report("recovery_detection_ms", out["detection_ms"], "ms")
    report("recovery_recovery_ms", out["recovery_ms"], "ms")
    report("recovery_mttr_ms", out["mttr_ms"], "ms")
    report("recovery_replayed_updates", out["replayed_updates"], "updates")
    report(
        "recovery_serve_degraded",
        out["serve"]["degraded_served"], "requests",
    )
    report(
        "recovery_disarmed_overhead_ratio",
        out["disarmed_overhead"]["ratio_median"], "x",
    )
    report(
        "recovery_disarmed_check_ns",
        out["disarmed_overhead"]["check_ns_per_call"], "ns/call",
    )
    report("recovery_bit_identical", 1.0, "bool")
    report(
        "recovery_bundle_ring_nodes", len(bb.get("nodes", {})), "nodes"
    )


def _sparse_touch_pattern(p: int, u: int, seed: int = 0):
    """A realistic deduped-touch draw for the sparse-update A/B: sorted
    unique slot ids (prep's np.unique output shape) for ~7/8 of the
    padded width, sentinel-style tail (clipped, ``ok`` False) for the
    rest — the localize contract apply_state_rows sees."""
    rng = np.random.default_rng(seed)
    live = rng.choice(p, min(u - u // 8, p // 2), replace=False)
    rel = np.full(u, p - 1, np.int32)
    rel[: len(live)] = np.sort(live).astype(np.int32)
    ok = np.zeros(u, bool)
    ok[: len(live)] = True
    g = rng.normal(size=u).astype(np.float32)
    return rel, ok, g, len(live)


def ftrl_sparse_ab(smoke: bool = False) -> dict:
    """XLA-rows vs fused-kernel A/B for the sparse-touched FTRL update
    (the ``update='sparse'`` big-table path, ops/ftrl_sparse.py).

    Both arms run the DONATED form (the production configuration: the
    fused step donates the table, so the kernel's in-place aliasing is
    copy-free) over identical state and touch patterns:

    - ``xla_rows`` — the gather→apply→scatter rows formulation
      (``ftrl_sparse_rows_ref``, today's apply_state_rows path): four
      separate XLA dispatches with intermediate row vectors.
    - ``fused``    — the Pallas gather→update→scatter kernel: one pass,
      scalar-prefetched row ids, double-buffered row DMAs, in-place
      write-back. Off-TPU this arm falls back to the same rows path
      (``fused_is_fallback: true`` — the A/B is then a record-shape
      smoke, not a speedup claim; re-measure on chip).

    Arms alternate back-to-back and the speedup quotes the MEDIAN of
    paired ratios (this host's CPU capacity flaps seconds-scale — the
    PR-3 bench discipline). ``hbm_gb_s``/``frac_of_peak`` use the
    disclosed bytes model below; ``onchip_target`` states the roofline
    goal the next device capture is judged against (ROADMAP item 4:
    10x the 0.007-0.015 dense-sweep frac of BENCH_r05)."""
    import time as _time

    import jax

    from ..ops.ftrl import _LANES, _use_pallas
    from ..ops.ftrl_sparse import ftrl_sparse_rows_ref, ftrl_sparse_update

    on_tpu = _use_pallas()
    p = 1 << (18 if smoke else 22)
    u = 1 << (11 if smoke else 16)
    kw = dict(alpha=0.1, beta=1.0, l1=0.05, l2=0.0)
    rel_h, ok_h, g_h, n_live = _sparse_touch_pattern(p, u)
    rows_touched = len(np.unique(rel_h[ok_h] // _LANES))
    rng = np.random.default_rng(1)
    z0 = rng.normal(size=p).astype(np.float32)
    n0 = np.abs(rng.normal(size=p)).astype(np.float32)
    rel = jax.device_put(rel_h)
    ok = jax.device_put(ok_h)
    g = jax.device_put(g_h)

    arms = {
        "xla_rows": jax.jit(
            lambda z, n: ftrl_sparse_rows_ref(z, n, rel, ok, g, **kw),
            donate_argnums=(0, 1),
        ),
        "fused": jax.jit(
            lambda z, n: ftrl_sparse_update(
                z, n, rel, ok, g, **kw, force_pallas=on_tpu
            ),
            donate_argnums=(0, 1),
        ),
    }
    boxes = {
        name: [jax.device_put(z0.copy()), jax.device_put(n0.copy())]
        for name in arms
    }
    for name, fn in arms.items():  # compile + warm, untimed
        boxes[name] = list(fn(*boxes[name]))
        jax.block_until_ready(boxes[name][0])

    reps = 3 if smoke else 5
    calls = 2 if smoke else 4
    times = {name: [] for name in arms}
    for _ in range(reps):
        for name, fn in arms.items():
            t0 = _time.perf_counter()
            for _ in range(calls):
                boxes[name] = list(fn(*boxes[name]))
            jax.block_until_ready(boxes[name][0])
            times[name].append((_time.perf_counter() - t0) / calls)
    ratios = sorted(
        x / f for x, f in zip(times["xla_rows"], times["fused"])
    )
    # medians for the headline ms too (not means): one capacity-flap
    # rep would otherwise make the quoted ms pair contradict the
    # paired-median speedup in the same record
    sec = {k: sorted(v)[len(v) // 2] for k, v in times.items()}

    # bytes model (disclosed, doc/PERFORMANCE.md "FTRL roofline"):
    # every indexed access to the f32 tables moves a 512 B 128-lane row
    # granule. fused: fetch + write-back of each DISTINCT touched row,
    # z and √n, plus the in-program [U,128] gradient scatter (write +
    # kernel read). xla_rows: 4 passes (gather z, gather √n, scatter
    # z', scatter √n'), each touching U row granules (duplicates not
    # deduped by XLA), plus the gathered/updated row vectors.
    row_b = _LANES * 4
    fused_bytes = rows_touched * row_b * 2 * 2 + u * row_b * 2
    xla_bytes = 4 * u * row_b + 4 * u * 4
    dev = jax.devices()[0]
    peak = HBM_PEAK_GB_S.get(dev.device_kind)
    fused_gb_s = fused_bytes / sec["fused"] / 1e9
    out = {
        "num_slots": p,
        "uniq_pad": u,
        "live_slots": n_live,
        "rows_touched": rows_touched,
        "backend": jax.default_backend(),
        "device_kind": dev.device_kind,
        "fused_is_fallback": not on_tpu,
        "xla_rows_ms": round(sec["xla_rows"] * 1e3, 3),
        "fused_ms": round(sec["fused"] * 1e3, 3),
        "fused_speedup_median_paired": round(
            ratios[len(ratios) // 2], 3
        ),
        "reps": reps,
        "calls_per_rep": calls,
        "bytes_model": {
            "fused_bytes_per_ministep": int(fused_bytes),
            "xla_rows_bytes_per_ministep": int(xla_bytes),
            "note": "512 B row granule per indexed access; fused = "
            "2 passes x distinct rows x {z,sqrt_n} + [U,128] grad "
            "scatter; xla_rows = 4 single-array passes x U accesses",
        },
        "hbm_gb_s": round(fused_gb_s, 2),
        "xla_rows_hbm_gb_s": round(xla_bytes / sec["xla_rows"] / 1e9, 2),
        "hbm_peak_gb_s": peak,
        "frac_of_peak": (
            round(fused_gb_s / peak, 4) if peak else None
        ),
        # the record-schema statement of the on-chip goal: BENCH_r05
        # measured the dense sweep at ftrl_hbm_frac_of_peak
        # 0.007-0.015; the fused sparse kernel's acceptance bar on the
        # next reachable-device capture is 10x that.
        "onchip_target": {
            "ftrl_hbm_frac_of_peak": ">= 0.07 (10x the 0.007-0.015 "
            "BENCH_r05 dense-sweep capture)",
            "measured_on": "next make bench-all with a reachable device",
        },
    }
    # XLA-derived bytes cross-check (device truth plane, telemetry/
    # device.py): the hand 512B-granule model above is the TPU DMA
    # story; cost_analysis() is the compiler's own count. The ratio is
    # DISCLOSED, not gated — XLA counts element bytes (no row-granule
    # rounding), so disagreement off-TPU is expected and its size says
    # how much of the hand model is granule overhead vs real traffic.
    from ..telemetry.device import aot_analyze

    analyses = {
        name: aot_analyze(fn, *boxes[name]) for name, fn in arms.items()
    }
    fused_an = analyses.get("fused") or {}
    rows_an = analyses.get("xla_rows") or {}
    if fused_an.get("bytes_accessed"):
        xla_fused_b = fused_an["bytes_accessed"]
        xla_gb_s = xla_fused_b / sec["fused"] / 1e9
        out["bytes_model_cross_check"] = {
            "hand_fused_bytes": int(fused_bytes),
            "xla_fused_bytes_accessed": int(xla_fused_b),
            "hand_over_xla_ratio": round(fused_bytes / xla_fused_b, 3),
            "hand_xla_rows_bytes": int(xla_bytes),
            "xla_rows_bytes_accessed": (
                int(rows_an["bytes_accessed"])
                if rows_an.get("bytes_accessed") else None
            ),
            "xla_fused_hbm_gb_s": round(xla_gb_s, 2),
            "frac_of_peak_xla": (
                round(xla_gb_s / peak, 4) if peak else None
            ),
            "fused_flops": (
                int(fused_an["flops"]) if fused_an.get("flops") else None
            ),
            "donation_aliased": (
                fused_an.get("alias_bytes", 0) > 0
                and not fused_an.get("donation_warned", False)
            ),
            "note": "hand = 512B row-granule DMA model; XLA cost "
            "analysis counts element bytes — the ratio is disclosure, "
            "not a gate (re-judge on a device capture)",
        }
    return out


def flash_cost_crosscheck(smoke: bool = False) -> dict:
    """Flash-attention fwd: hand FLOPs model vs XLA cost analysis.

    The MFU tables (doc/PERFORMANCE.md "Byte-LM training MFU") divide
    by the hand ``4·bh·s²·d`` convention; this probe asks the compiler
    what it actually counted at the same shape and disclosed the ratio
    — the flash half of the bench record's roofline cross-check. Runs
    the XLA formulation on every backend (a Pallas custom call is
    opaque to cost analysis); one timed flush gives the achieved
    TFLOP/s both models imply, with frac-of-peak only where the peak
    table knows the chip."""
    import time as _time

    import jax

    from ..ops.flash_attention import flash_attention
    from ..telemetry.device import aot_analyze
    from . import FLOPS_PEAK_TFLOPS

    bh, d = 4, 64
    s = 256 if smoke else 1024
    rng = np.random.default_rng(0)
    q, k, v = (
        jax.device_put(rng.normal(size=(bh, s, d)).astype(np.float32))
        for _ in range(3)
    )
    fn = jax.jit(
        lambda qq, kk, vv: flash_attention(
            qq, kk, vv, causal=True, use_pallas=False
        )
    )
    hand_flops = 4.0 * bh * s * s * d
    an = aot_analyze(fn, q, k, v) or {}
    jax.block_until_ready(fn(q, k, v))  # compile + warm untimed
    reps = 3
    t0 = _time.perf_counter()
    for _ in range(reps):
        jax.block_until_ready(fn(q, k, v))
    sec = (_time.perf_counter() - t0) / reps
    dev = jax.devices()[0]
    peak = FLOPS_PEAK_TFLOPS.get(dev.device_kind)
    out = {
        "shape_bh_s_d": [bh, s, d],
        "device_kind": dev.device_kind,
        "hand_flops": int(hand_flops),
        "hand_tflops": round(hand_flops / sec / 1e12, 5),
        "xla_path": True,  # cost analysis needs the non-Pallas program
    }
    if an.get("flops"):
        out["xla_flops"] = int(an["flops"])
        out["hand_over_xla_ratio"] = round(hand_flops / an["flops"], 3)
        out["xla_tflops"] = round(an["flops"] / sec / 1e12, 5)
    if an.get("bytes_accessed"):
        out["xla_bytes_accessed"] = int(an["bytes_accessed"])
    if peak:
        out["mfu_hand"] = round(hand_flops / sec / 1e12 / peak, 6)
        if an.get("flops"):
            out["mfu_xla"] = round(an["flops"] / sec / 1e12 / peak, 6)
    else:
        out["mfu_hand"] = None  # CPU host: no faked peak (HBM table rule)
    return out


@benchmark("ftrl_sparse_ab")
def ftrl_sparse_perf(smoke: bool = False) -> None:
    """Sparse-update A/B (see ftrl_sparse_ab). The same dict is
    embedded in every bench.py record under ``ftrl_sparse``."""
    out = ftrl_sparse_ab(smoke)
    report("ftrl_sparse_xla_rows_ms", out["xla_rows_ms"], "ms")
    report("ftrl_sparse_fused_ms", out["fused_ms"], "ms")
    report(
        "ftrl_sparse_fused_speedup",
        out["fused_speedup_median_paired"], "x",
    )
    report("ftrl_sparse_fused_hbm_gb_s", out["hbm_gb_s"], "GB/s")
    # `is not None`, NOT truthiness: a frac that rounds to 0.0 is a
    # catastrophic roofline regression the capture must report
    if out["frac_of_peak"] is not None:
        report(
            "ftrl_sparse_fused_frac_of_peak", out["frac_of_peak"],
            "fraction",
        )


@benchmark("ftrl_chain")
def ftrl_chain_perf(smoke: bool = False) -> None:
    """Dense-formulation chain A/B: 8 chained FTRL updates per
    dispatch, donated form — the corrected measurement the
    ``ops/ftrl.xla_min_slots`` docstring has been awaiting. The
    single-update on-chip captures (BENCH_ONCHIP 2026-08-02) were
    confounded twice over: XLA inserted defensive whole-table copies
    for the non-donated Pallas aliasing, and a ~14.5 ms per-dispatch
    tunnel floor buried both arms. Chaining 8 updates inside ONE
    donated dispatch amortizes the dispatch floor 8x and gives the
    kernel its production aliasing, so the per-update delta is the
    formulation difference. Emits ``ftrl_dense_{pallas,xla}_2e{K}_
    chain_*`` — the metric names BENCH_ONCHIP.md's next ``make
    bench-all`` capture appends, against which the 2^62 default is
    re-judged (flip point = smallest size where the pallas per-update
    median beats xla's; derivation in doc/PERFORMANCE.md)."""
    import jax

    from ..ops.ftrl import _use_pallas, ftrl_update, ftrl_update_ref

    on_tpu = _use_pallas()
    chain_len = 8
    kw = dict(alpha=0.1, beta=1.0, l1=0.05, l2=0.0)
    if smoke:
        sizes = (1 << 14,)
    elif on_tpu:
        sizes = (1 << 24, 1 << 26, 1 << 28)
    else:
        sizes = (1 << 18, 1 << 20)

    def make_chain(pallas: bool):
        def chain(z, n, g):
            for _ in range(chain_len):
                if pallas:
                    z, n = ftrl_update(z, n, g, None, **kw,
                                       force_pallas=True)
                else:
                    z, n = ftrl_update_ref(z, n, g, None, **kw)
            return z, n

        return jax.jit(chain, donate_argnums=(0, 1))

    for p in sizes:
        tag = f"2e{p.bit_length() - 1}"
        rng = np.random.default_rng(0)
        z0 = rng.normal(size=p).astype(np.float32)
        n0 = np.abs(rng.normal(size=p)).astype(np.float32)
        g = jax.device_put(rng.normal(size=p).astype(np.float32))
        arms = {"xla": make_chain(False)}
        # off-TPU the forced-Pallas arm cannot run (no interpret in a
        # timed bench); the xla arm still pins the record shape
        if on_tpu:
            arms["pallas"] = make_chain(True)
        for name, fn in arms.items():
            box = [jax.device_put(z0.copy()), jax.device_put(n0.copy())]
            box = list(fn(*box, g))  # compile untimed
            jax.block_until_ready(box[0])

            def once(fn=fn, box=box):
                box[:] = fn(*box, g)
                jax.block_until_ready(box[0])

            sec = timeit(once, 2 if smoke else 5, budget_s=30.0)
            report(f"ftrl_dense_{name}_{tag}_chain_ms", sec * 1e3, "ms")
            report(
                f"ftrl_dense_{name}_{tag}_chain_per_update_ms",
                sec / chain_len * 1e3, "ms",
            )
            # dense sweep traffic: z rw + sqrt_n rw = 16 B/slot/update
            report(
                f"ftrl_dense_{name}_{tag}_chain_gb_s",
                16.0 * p * chain_len / sec / 1e9, "GB/s",
            )


@benchmark("roofline")
def roofline_probe(smoke: bool = False) -> None:
    """``make roofline``: drive the device truth plane end to end on
    the live backend (telemetry/device.py).

    Two representative kernels — a dense FTRL chain (HBM-bound) and a
    flash-attention fwd (FLOPs-bound) — run through instrumented
    wrappers with per-call roofline sampling, so each dispatch lands
    its measured wall time against its own XLA cost analysis. Reports
    achieved GB/s / TFLOP/s per kernel, frac-of-peak where the peak
    tables know the chip (CPU hosts report the achieved rates only —
    the frac is never faked), and the inventory's compile/recompile
    sanity (a steady-shape probe must recompile zero times after its
    first call). The same families are node-labeled on /metrics
    (``ps_device_kernel_*``, ``ps_device_roofline_frac``)."""
    import jax

    from ..ops.flash_attention import flash_attention
    from ..ops.ftrl import ftrl_update_ref
    from ..telemetry import device as device_tel

    inv = device_tel.DeviceInventory()
    inv.set_sampling(1)  # every dispatch timed: this is a measurement run
    rng = np.random.default_rng(0)

    # HBM-bound probe: 8 chained dense FTRL updates in one program
    p = 1 << (14 if smoke else 20)
    kw = dict(alpha=0.1, beta=1.0, l1=0.05, l2=0.0)

    def chain(z, n, g):
        for _ in range(8):
            z, n = ftrl_update_ref(z, n, g, None, **kw)
        return z, n

    ftrl_fn = inv.instrument(
        "roofline_ftrl_chain",
        jax.jit(chain, donate_argnums=(0, 1)),
        donate_argnums=(0, 1),
    )
    box = [
        jax.device_put(rng.normal(size=p).astype(np.float32)),
        jax.device_put(np.abs(rng.normal(size=p)).astype(np.float32)),
    ]
    g = jax.device_put(rng.normal(size=p).astype(np.float32))
    for _ in range(3 if smoke else 5):
        box = list(ftrl_fn(*box, g))
    jax.block_until_ready(box[0])

    # FLOPs-bound probe: flash fwd, XLA formulation (cost-analyzable)
    bh, s, d = 4, 256 if smoke else 1024, 64
    q, k, v = (
        jax.device_put(rng.normal(size=(bh, s, d)).astype(np.float32))
        for _ in range(3)
    )
    flash_fn = inv.instrument(
        "roofline_flash_fwd",
        jax.jit(
            lambda qq, kk, vv: flash_attention(
                qq, kk, vv, causal=True, use_pallas=False
            )
        ),
    )
    for _ in range(3 if smoke else 5):
        jax.block_until_ready(flash_fn(q, k, v))

    snap = inv.snapshot()
    recompiles = sum(
        rec["recompiles"] for rec in snap["functions"].values()
    )
    report("roofline_functions", len(snap["functions"]), "fns")
    report("roofline_steady_recompiles_plus_one", recompiles + 1, "compiles")
    for name, rec in sorted(snap["functions"].items()):
        tl = rec.get("roofline") or {}
        # every guard below is `is not None`, not truthiness — an
        # achieved rate or frac that rounds to 0.0 is a catastrophic
        # regression the capture must report, not omit (PR 8 rule)
        if tl.get("achieved_gb_s") is not None:
            report(f"{name}_gb_s", tl["achieved_gb_s"], "GB/s")
        if tl.get("achieved_tflops") is not None:
            # GFLOP/s (and pct below): report()'s 2-decimal rounding
            # would flatten a CPU-host TFLOP/s figure to 0.0
            report(f"{name}_gflops", tl["achieved_tflops"] * 1e3,
                   "GFLOP/s")
        if tl.get("frac_of_hbm_peak") is not None:
            report(f"{name}_hbm_peak_pct",
                   tl["frac_of_hbm_peak"] * 100.0, "pct")
        if tl.get("mfu") is not None:
            report(f"{name}_mfu_pct", tl["mfu"] * 100.0, "pct")


def rebalance_drill(smoke: bool = False) -> dict:
    """Heat-driven live-repartitioning drill on a forced 8-device mesh
    (doc/PERFORMANCE.md "Declarative partitioning" — the ISSUE's
    one-mesh-every-chip acceptance, embedded in MULTICHIP-style records
    under ``rebalance``).

    The script:

    1. **mesh** — auto-shaping is demonstrated (num_server=3 on 8
       devices becomes 4x2, never 3x2-with-2-idle) and the drill mesh
       (1x8: 8 server shards, so the max/mean imbalance ratio CAN
       exceed the shipped 4.0 threshold) is asserted to use 8/8
       devices, 0 idle.
    2. **parity** — a table spanning 2 server shards (4x2) trains
       bit-identically to the single-shard path (4x1) on the same
       data-axis width.
    3. **skew → alert → rebalance** — a live train stream (80% of
       traffic on one shard's keys) feeds the KeyHeat plane; the
       measured imbalance rides the ``ps_learning_shard_imbalance``
       gauge into the SHIPPED ``shard_imbalance`` rule; the attached
       RebalanceController plans from the hot-slot/load-share tables
       and migrates rows online through the consistent-snapshot
       machinery while a ``rebalance.migrate`` delay fault widens the
       journal window (pushes landing mid-move must journal + replay).
    4. **verify** — a closed-loop serve stream across the move
       completes EVERY request (degraded-to-lock-latency allowed,
       errors not); post-rebalance traffic re-measures imbalance below
       the alert threshold; the final base-layout table is
       bit-identical to an undisturbed run; and the live phase compiles
       nothing new (``recompiles_post_warmup == 0``).
    """
    import threading
    import time as _time

    import jax

    from ..parallel import mesh as meshlib
    from ..parallel import partition as partlib
    from ..parameter.kv_vector import KVVector
    from ..system import faults
    from ..system.postoffice import Postoffice
    from ..telemetry import alerts as alerts_mod
    from ..telemetry import device as _device
    from ..telemetry import registry as telemetry_registry
    from ..telemetry.instruments import learning_instruments
    from ..telemetry.learning import KeyHeat

    n_dev = len(jax.devices())
    assert n_dev == 8, (
        f"rebalance drill needs the forced 8-device platform, got "
        f"{n_dev} (run via `make rebalance-bench`)"
    )
    Postoffice.reset()
    faults.reset()
    _device.reset()

    # -- 1. mesh: auto-shape demo + the 1x8 drill mesh, 0 idle --------
    demo = meshlib.make_mesh(num_server=3)  # -> 4x2, never 3x2+2 idle
    assert demo.devices.size == n_dev, dict(demo.shape)
    assert dict(demo.shape) == {meshlib.DATA_AXIS: 4, meshlib.SERVER_AXIS: 2}
    mesh = meshlib.make_mesh(num_data=1, num_server=8)
    mesh_section = {
        "devices_total": n_dev,
        "devices_used": int(mesh.devices.size),
        "devices_idle": n_dev - int(mesh.devices.size),
        "shape": {"data": int(mesh.shape[meshlib.DATA_AXIS]),
                  "server": int(mesh.shape[meshlib.SERVER_AXIS])},
        "auto_shape_demo": {
            "requested_server": 3,
            "chosen": {"data": int(demo.shape[meshlib.DATA_AXIS]),
                       "server": int(demo.shape[meshlib.SERVER_AXIS])},
            "devices_idle": n_dev - int(demo.devices.size),
        },
    }
    assert mesh_section["devices_idle"] == 0, mesh_section
    assert mesh_section["auto_shape_demo"]["devices_idle"] == 0

    k = 4
    keys = np.arange(48, dtype=np.int64)
    hot = keys[:8]  # one server shard's key range (slots 0..7 of 64)
    n_batches = 160 if smoke else 280
    batch_n = 64

    def mk_batch(i: int):
        r = np.random.default_rng(1000 + i)
        pick_hot = r.random(batch_n) < 0.8
        ks = np.where(
            pick_hot,
            r.choice(hot, size=batch_n),
            r.choice(keys[8:], size=batch_n),
        ).astype(np.int64)
        vals = r.normal(size=(batch_n, k)).astype(np.float32)
        return ks, vals

    batches = [mk_batch(i) for i in range(n_batches)]

    def new_store(name: str, m=mesh) -> KVVector:
        kv = KVVector(mesh=m, k=k, num_slots=64, hashed=False, name=name)
        kv.set_keys(0, keys)
        return kv

    def train(kv: KVVector, bs) -> np.ndarray:
        for ks, vs in bs:
            kv.push(kv.request(channel=0), keys=ks, values=vs)
        kv.executor.wait_all(pop=False)
        return kv.get_replica()[0]

    # -- 2. >1-server-shard table trains bit-identically to 1-shard ---
    devs = jax.devices()[:4]
    single = train(
        new_store("reb_1shard",
                  meshlib.make_mesh(num_data=4, num_server=1,
                                    devices=devs)),
        batches[:6],
    )
    multi = train(
        new_store("reb_2shard",
                  meshlib.make_mesh(num_data=4, num_server=2)),
        batches[:6],
    )
    parity_single_multi = single.tobytes() == multi.tobytes()
    assert parity_single_multi, (
        "2-server-shard table diverged from the single-shard run"
    )

    # -- undisturbed reference (doubles as shape warmup for the live
    # run: push [64,k], pull [48], snapshot/install/replay) -----------
    ref = new_store("reb_ref")
    ref_table = train(ref, batches)
    np.asarray(ref.wait_pull(ref.pull(ref.request(channel=0), keys=keys)))
    scratch = new_store("reb_scratch")
    train(scratch, batches[:1])
    scratch.migrate(np.random.default_rng(2).permutation(64))
    np.asarray(
        scratch.wait_pull(
            scratch.pull(scratch.request(channel=0), keys=keys)
        )
    )
    _device.mark_warmup()

    # -- 3. the live phase: skewed train + serve + alert + controller -
    kv = new_store("reb_live")
    heat = KeyHeat(num_slots=kv.num_slots, num_shards=8, top_k=16,
                   decay_every=1 << 30)
    ctl = partlib.RebalanceController(kv, heat)
    reg = telemetry_registry.default_registry()
    gauge = learning_instruments(reg)["shard_imbalance"]
    mgr = alerts_mod.AlertManager(alerts_mod.default_rules(),
                                  registry=reg)
    transitions = []
    mgr.add_listener(
        lambda ev: transitions.append(f"{ev.frm}->{ev.to}")
        if ev.rule == "shard_imbalance" else None
    )
    ctl.attach(mgr)
    # widen the copy window so the serve/train streams demonstrably
    # cross the move (journaled + replayed pushes > 0)
    faults.arm("rebalance.migrate", kind="delay", delay_s=0.25,
               once=True)

    progress = {"t": 0.0, "acked": 0}
    serve_stats = {"ok": 0, "failed": 0}
    stop_serve = threading.Event()

    def serve():
        while not stop_serve.is_set():
            try:
                got = kv.wait_pull(
                    kv.pull(kv.request(channel=0), keys=keys)
                )
                np.asarray(got)
                serve_stats["ok"] += 1
            except Exception:
                serve_stats["failed"] += 1
            _time.sleep(0.001)

    def trainer():
        for i, (ks, vs) in enumerate(batches):
            kv.push(kv.request(channel=0), keys=ks, values=vs)
            progress["acked"] += 1
            heat.note(np.asarray(kv.slots(0, ks)))
            imb = heat.shares().get("imbalance")
            if imb is not None:
                gauge.set(imb)
            progress["t"] = float(i + 1)  # the drill's logical clock
            _time.sleep(0.002)

    serve_t = threading.Thread(target=serve, name="reb-serve")
    train_t = threading.Thread(target=trainer, name="reb-train")
    serve_t.start()
    train_t.start()
    # evaluate the shipped rules on the drill's LOGICAL clock (batch
    # index), so the for_s dwell is deterministic, not host-paced
    while train_t.is_alive():
        mgr.evaluate(now=progress["t"])
        _time.sleep(0.004)
    train_t.join()
    mgr.evaluate(now=progress["t"] + 6.0)  # let the alert resolve
    stop_serve.set()
    serve_t.join(timeout=30)
    kv.executor.wait_all(pop=False)

    # -- 4. verify ----------------------------------------------------
    hist = ctl.history()
    assert len(hist) == 1, (
        f"expected exactly one alert-triggered rebalance, got {hist}"
    )
    rec = dict(hist[0])
    assert kv.layout(0) is not None
    assert rec["journaled_pushes"] > 0 and rec["replayed_pushes"] > 0, (
        "the move missed the live stream: nothing journaled/replayed "
        f"({rec})"
    )
    post_imb = ctl.refresh_post_imbalance()
    assert post_imb is not None and post_imb < ctl.threshold, (
        f"post-rebalance imbalance {post_imb} still over "
        f"{ctl.threshold}"
    )
    assert serve_stats["failed"] == 0 and serve_stats["ok"] > 0, (
        f"serve stream across the migration broke: {serve_stats}"
    )
    live_table = kv.get_replica()[0]
    bit_identical = live_table.tobytes() == ref_table.tobytes()
    assert bit_identical, (
        "post-migration table diverged from the undisturbed run"
    )
    dev_snap = _device.snapshot()
    rpw = dev_snap.get("recompiles_post_warmup")
    assert rpw == 0, (
        f"live rebalance phase compiled new programs: {rpw}"
    )

    return {
        "mesh": mesh_section,
        "rebalance": {
            "alert": {
                "rule": "shard_imbalance",
                "threshold": ctl.threshold,
                "transitions": transitions,
            },
            "imbalance_before": rec["imbalance_before"],
            "predicted_imbalance": rec["predicted_imbalance"],
            "post_rebalance_imbalance": round(float(post_imb), 4),
            "rows_moved": rec["rows_moved"],
            "moves": rec["moves"],
            "migration_seconds": rec["migration_seconds"],
            "journaled_pushes": rec["journaled_pushes"],
            "replayed_pushes": rec["replayed_pushes"],
            "attempts": rec["attempts"],
            "barrier_ts": rec["barrier_ts"],
            "install_ts": rec["install_ts"],
            "acked_pushes": progress["acked"],
            "serve": {
                "requests": serve_stats["ok"] + serve_stats["failed"],
                "completed_ok": serve_stats["ok"],
                "failed": serve_stats["failed"],
            },
            "sharded_vs_single_bit_identical": parity_single_multi,
            "trajectory_bit_identical": bit_identical,
            "recompiles_post_warmup": rpw,
        },
        "device": {
            "recompiles_post_warmup": rpw,
            "backend": dev_snap.get("backend"),
            "device_kind": dev_snap.get("device_kind"),
        },
    }


@benchmark("rebalance")
def rebalance_perf(smoke: bool = False) -> None:
    """`make rebalance-bench`: the heat-driven live-repartitioning
    acceptance drill. Every contract is asserted inside
    :func:`rebalance_drill`; this wrapper reports the headline numbers
    and writes the full record where ``PS_REBALANCE_OUT`` points
    (default ``<tmp>/ps_rebalance.json``) for MULTICHIP-style capture."""
    import json as _json
    import os as _os
    import tempfile as _tempfile

    out_path = _os.environ.get("PS_REBALANCE_OUT") or _os.path.join(
        _tempfile.gettempdir(), "ps_rebalance.json"
    )
    out = rebalance_drill(smoke)
    reb = out["rebalance"]
    report("rebalance_imbalance_before", reb["imbalance_before"], "ratio")
    report("rebalance_post_imbalance", reb["post_rebalance_imbalance"],
           "ratio")
    report("rebalance_rows_moved", reb["rows_moved"], "rows")
    report("rebalance_migration_seconds", reb["migration_seconds"], "s")
    report("rebalance_replayed_pushes", reb["replayed_pushes"], "pushes")
    # serve failures are asserted == 0 inside the drill and recorded in
    # the JSON record; report the completions (always > 0) instead
    report("rebalance_serve_ok", reb["serve"]["completed_ok"], "requests")
    with open(out_path, "w") as f:
        _json.dump({"rebalance_record": out}, f, indent=2)

def _consistency_conf(tau, *, adaptive=False, kkt=False, drop_after=0):
    """One consistency-arm config. The τ arms run the stability-frontier
    workload (standard SGD, square loss, constant α at the edge where
    delayed gradients visibly cost accuracy); the KKT arms run the FTRL
    + L1 workload the filter's threshold is derived from."""
    from ..apps.linear.config import (
        Config,
        LearningRateConfig,
        LossConfig,
        PenaltyConfig,
        SGDConfig,
    )

    conf = Config()
    if kkt:
        conf.penalty = PenaltyConfig(type="l1", lambda_=[0.1])
        conf.learning_rate = LearningRateConfig(
            type="decay", alpha=0.1, beta=1.0
        )
        conf.async_sgd = SGDConfig(
            algo="ftrl", minibatch=128, num_slots=1 << 10, max_delay=tau,
            update="sparse", tau_adaptive=adaptive, kkt_filter=True,
            kkt_drop_after=drop_after,
            kkt_revisit_every=8,
            ingest_workers=1,
        )
    else:
        conf.loss = LossConfig(type="square")
        conf.penalty = PenaltyConfig(type="l2", lambda_=[0.0])
        # α at the delayed-stability frontier: τ=0 converges cleanly,
        # τ=max pays a measured final-loss penalty from stale
        # gradients (the NIPS'14 bounded-delay degradation, made
        # visible on purpose) — the regime where an adaptive τ earns
        # its keep
        conf.learning_rate = LearningRateConfig(
            type="constant", alpha=0.03, beta=1.0
        )
        conf.async_sgd = SGDConfig(
            algo="standard", minibatch=128, num_slots=1 << 10,
            max_delay=tau, tau_adaptive=adaptive,
        )
    return conf


def _consistency_batches(n, directory, num_slots, seed0=0):
    """Planted-regression batches, labeled through the SAME key→slot
    hash the workers use, so every arm sees an identical learnable
    problem with a known optimum."""
    from ..utils.sparse import random_sparse

    rng = np.random.default_rng(7)
    wstar = rng.normal(size=num_slots).astype(np.float32)
    noise = np.random.default_rng(11)
    out = []
    for i in range(n):
        b = random_sparse(128, 1 << 14, 8, seed=seed0 + i, binary=True)
        slots = directory.slots(b.indices)
        rows = b.row_ids()
        xw = np.zeros(b.n, np.float32)
        np.add.at(xw, rows, wstar[np.minimum(slots, num_slots - 1)])
        b.y = (xw / 8.0 + 0.05 * noise.normal(size=b.n)).astype(np.float32)
        out.append(b)
    return out


def _final_loss(worker_name) -> float:
    from ..telemetry import learning as learning_mod

    snap = learning_mod.get_plane(worker_name).snapshot()
    tail = [
        p["loss"] for p in snap["trajectory_tail"][-8:]
        if isinstance(p["loss"], float)
    ]
    return float(np.median(tail)) if tail else float("inf")


def _attach_pull_rtt(worker, rtt_s: float) -> None:
    """Emulate the cross-host weight-pull RTT on snapshot-refresh
    submissions — the latency τ exists to hide (OSDI'14's wait-time
    model: a worker blocks on a fresh pull only when its snapshot has
    aged past the delay bound).

    DISCLOSED in-record as ``emulated_pull_rtt_ms``: on this CPU
    container host and device share the same cores, so the real
    overlap win of bounded staleness cannot physically show (there is
    no idle resource for τ>0 to reclaim — measured here as ±25%
    run-to-run noise around a flat line). The sleep lands exactly
    where a multi-host deployment blocks: at the submit that refreshes
    the pulled snapshot (async_sgd.py's ``do_snapshot``), so τ=0 pays
    it every step, τ=max every τ-th, and the adaptive arm at its
    CURRENT live τ — the loss trajectories stay real measurements,
    untouched by the emulation."""
    import time as _time

    orig = worker._submit_prepped

    def submit(prepped, with_aux: bool = True) -> int:
        tau = worker._effective_tau
        if tau <= 0 or worker._steps_since_snapshot >= tau:
            _time.sleep(rtt_s)
        return orig(prepped, with_aux=with_aux)

    worker._submit_prepped = submit


def _consistency_divergence_drill(mesh, smoke: bool) -> dict:
    """Seeded divergence drill through the CONTROLLER's reaction path:
    a poisoned batch (non-finite labels) NaNs one collected step; the
    learning plane judges it divergent (the shipped ``loss_divergence``
    rule fires on the counter, fake clock), and the adaptive controller
    reacts in the same collect — τ→0, automatic LR backoff, rollback to
    its last healthy snapshot — then the run re-converges on clean
    data. The whole episode lands in ONE flight-recorder bundle: the
    controller's own ``consistency_rollback`` trigger captures while
    the pre-divergence evidence is still in the rings."""
    from ..apps.linear.async_sgd import AsyncSGDWorker
    from ..telemetry import alerts as alerts_mod
    from ..telemetry import blackbox
    from ..telemetry import learning as learning_mod

    rule = next(
        r for r in alerts_mod.default_rules() if r.name == "loss_divergence"
    )
    clock = [0.0]
    mgr = alerts_mod.AlertManager([rule], clock=lambda: clock[0])
    prev_interval = blackbox.set_min_interval(0.0)
    was_armed = blackbox.installed_recorder() is not None
    blackbox.arm()
    blackbox.recorder().clear()  # a prior drill in this process must
    # not leak into this bundle
    conf = _consistency_conf(4, adaptive=True)
    worker = AsyncSGDWorker(conf, mesh=mesh, name="consistency_diverge")
    n_good = 8 if smoke else 12
    bundles0 = len(blackbox.bundles())
    try:
        mgr.evaluate()  # t=0 baseline sample — a rate needs a window
        batches = _consistency_batches(
            n_good + 4, worker.directory, worker.num_slots, seed0=300
        )
        losses = []
        for b in batches[:n_good]:
            ts = worker._submit_prepped(
                worker.prep(b, device_put=False), with_aux=False
            )
            worker.collect(ts)
            losses.append(_final_loss("consistency_diverge"))
        pre_alpha = float(worker.lr.alpha)
        pre_tau = worker._consistency.controller.tau
        bad = batches[n_good]
        bad.y = np.full_like(bad.y, np.float32("inf"))
        ts = worker._submit_prepped(
            worker.prep(bad, device_put=False), with_aux=False
        )
        worker.collect(ts)  # the reaction happens inside this collect
        clock[0] = 5.0
        mgr.evaluate()  # pending → firing in one tick (for_s=0)
        fired = rule.name in mgr.firing()
        post = []
        for b in batches[n_good + 1:]:
            ts = worker._submit_prepped(
                worker.prep(b, device_put=False), with_aux=False
            )
            worker.collect(ts)
            post.append(_final_loss("consistency_diverge"))
        episodes = list(worker._consistency.controller.episodes)
        plane = learning_mod.get_plane("consistency_diverge")
        divergences = dict(plane.snapshot()["divergence"])
        bundles = blackbox.bundles()[bundles0:]
        rollback_bundle = next(
            (
                b for b in bundles
                if b["trigger"]["kind"] == "consistency_rollback"
            ),
            None,
        )
    finally:
        worker.executor.stop()
        blackbox.set_min_interval(prev_interval)
        if not was_armed:
            blackbox.disarm()
    return {
        "good_steps": n_good,
        "loss_before_poison": losses[-1] if losses else None,
        "pre_reaction": {"alpha": pre_alpha, "tau": pre_tau},
        "episodes": episodes,
        "divergence_counts": divergences,
        "alert_fired": bool(fired),
        "post_rollback_losses": [round(x, 6) for x in post],
        "reconverged": bool(post)
        and all(np.isfinite(post))
        and post[-1] <= losses[0],
        "bundle_captured": rollback_bundle is not None,
        "bundle_trigger": (
            dict(rollback_bundle["trigger"]) if rollback_bundle else None
        ),
    }


def consistency_ab(smoke: bool = False) -> dict:
    """Self-driving consistency A/B (ISSUE 20), embedded under
    ``consistency`` in every bench record and run standalone via
    ``make consistency-bench``.

    Three τ arms on ONE workload (the delayed-stability frontier:
    planted regression, constant α where staleness measurably costs
    accuracy), back-to-back paired reps with medians: fixed τ=0
    (serialized, fresh gradients), fixed τ=max (full async overlap,
    stale gradients), and adaptive (the controller earns τ from
    stability). The frontier claim quoted in-record: adaptive ≥ τ=0 on
    e2e throughput AND < τ=max on final loss. Then the KKT significance
    filter off/on on the FTRL+L1 workload it is derived from — shipped
    keys/bytes measured with the suppression counters reconciled
    against ``ps_push_keys_total`` in-record, final-loss delta
    disclosed (the filter is lossy BY DESIGN) — and the seeded
    divergence drill through the controller's backoff + rollback
    reaction. Record METADATA, never banded by the bench-diff sentinel
    (script/bench_diff.py METADATA_SECTIONS)."""
    import time as _time

    from ..apps.linear.async_sgd import AsyncSGDWorker
    from ..parallel import mesh as meshlib
    from ..telemetry import learning as learning_mod
    from ..telemetry import registry as telemetry_registry
    from ..telemetry.instruments import parameter_instruments

    mesh = _learning_mesh()
    tau_max = 8
    n_batches = 24 if smoke else 64
    n_warm = 4
    reps = 1 if smoke else 3
    rtt_s = 0.025  # emulated pull RTT — see _attach_pull_rtt

    # one shared batch list, labeled through the shared hash (every
    # worker with the same num_slots config hashes identically)
    probe = AsyncSGDWorker(
        _consistency_conf(0), mesh=mesh, name="consistency_probe"
    )
    batches = _consistency_batches(
        n_batches, probe.directory, probe.num_slots
    )
    probe.executor.stop()

    arms = {}
    arm_specs = (
        ("tau0", 0, False),
        ("taumax", tau_max, False),
        ("adaptive", tau_max, True),
    )
    for rep in range(reps):
        for arm_name, tau, adaptive in arm_specs:
            name = f"consistency_{arm_name}_{rep}"
            worker = AsyncSGDWorker(
                _consistency_conf(tau, adaptive=adaptive),
                mesh=mesh, name=name,
            )
            _attach_pull_rtt(worker, rtt_s)
            if adaptive and worker._consistency is not None:
                # ramp scaled to the 60-batch window: the production
                # default (+1 per 8 healthy collects,
                # learner/consistency.py STABLE_STEPS) would spend the
                # ENTIRE bench run below cap — disclosed in-record as
                # adaptive_stable_steps
                worker._consistency.controller.stable_steps = 2
            try:
                worker.train(iter(batches[:n_warm]))  # compile warmup
                t0 = _time.perf_counter()
                worker.train(iter(batches[n_warm:]))
                dt = _time.perf_counter() - t0
            finally:
                worker.executor.stop()
            st = learning_mod.get_plane(name).snapshot()["staleness"]
            rec = arms.setdefault(
                arm_name,
                {"tau": tau, "adaptive": adaptive, "reps": [],
                 "final_loss": None, "staleness": None},
            )
            rec["reps"].append(
                round((n_batches - n_warm) * 128 / dt, 1)
            )
            if rep == 0:
                rec["final_loss"] = round(_final_loss(name), 6)
                rec["staleness"] = st
                if adaptive and worker._consistency is not None:
                    rec["controller"] = worker._consistency.snapshot()["tau"]
    for rec in arms.values():
        rec["examples_per_s_median"] = float(np.median(rec["reps"]))

    # paired-rep discipline: each rep ran all arms back-to-back, so
    # the adaptive-vs-τ0 throughput verdict is the median of PER-REP
    # ratios (machine drift cancels pairwise), not a ratio of medians
    pair_ratios = [
        a / b
        for a, b in zip(arms["adaptive"]["reps"], arms["tau0"]["reps"])
    ]
    frontier = {
        "adaptive_vs_tau0_throughput_ratio": round(
            float(np.median(pair_ratios)), 4
        ),
        "adaptive_beats_tau0_throughput": float(
            np.median(pair_ratios)
        ) > 1.0,
        "adaptive_beats_taumax_loss": (
            arms["adaptive"]["final_loss"] < arms["taumax"]["final_loss"]
        ),
        "tau0_loss": arms["tau0"]["final_loss"],
        "taumax_loss": arms["taumax"]["final_loss"],
        "adaptive_loss": arms["adaptive"]["final_loss"],
    }

    # -- KKT significance filter off/on (FTRL + L1, update='sparse') --
    kkt_batches = _consistency_batches(
        12 if smoke else 32, probe.directory, probe.num_slots, seed0=100
    )
    for b in kkt_batches:  # classification labels for the logit loss
        b.y = np.where(b.y > 0, 1.0, -1.0).astype(np.float32)
    kkt = {}
    for arm_name, on in (("off", False), ("on", True)):
        name = f"consistency_kkt_{arm_name}"
        conf = (
            _consistency_conf(2, kkt=True, drop_after=3)
            if on
            else _consistency_conf(2, kkt=True)
        )
        if not on:
            conf.async_sgd.kkt_filter = False
        worker = AsyncSGDWorker(conf, mesh=mesh, name=name)
        # counters are process-global per label set: reconcile against
        # the DELTA so a prior run of this bench in the same process
        # (the test suite smoke-runs every REGISTRY entry) can't skew
        counter0 = 0.0
        if on and telemetry_registry.enabled():
            counter0 = parameter_instruments(
                telemetry_registry.default_registry()
            )["push_keys"].value(store=name, channel=0)
        try:
            worker.train(iter(kkt_batches))
        finally:
            worker.executor.stop()
        entry = {"final_loss": round(_final_loss(name), 6)}
        if on:
            summary = worker._consistency.tracker.summary()
            counter = None
            if telemetry_registry.enabled():
                counter = parameter_instruments(
                    telemetry_registry.default_registry()
                )["push_keys"].value(store=name, channel=0) - counter0
            baseline_nnz = sum(b.nnz for b in kkt_batches)
            entry.update(
                {
                    "accounting": summary,
                    "push_keys_counter": counter,
                    "counter_reconciled": (
                        counter is None or counter == summary["pushed"]
                    ),
                    "suppressed_key_frac": round(
                        summary["suppressed"] / max(1, summary["candidates"]),
                        4,
                    ),
                    "baseline_nnz": baseline_nnz,
                    "dropped_entry_frac": round(
                        summary["dropped_entries"] / max(1, baseline_nnz), 4
                    ),
                }
            )
        kkt[arm_name] = entry
    kkt["loss_delta"] = round(
        kkt["on"]["final_loss"] - kkt["off"]["final_loss"], 6
    )

    return {
        "workload": {
            "n_batches": n_batches,
            "warmup_batches": n_warm,
            "minibatch": 128,
            "num_slots": probe.num_slots,
            "num_shards": meshlib.num_servers(mesh),
            "tau_max": tau_max,
            "reps": reps,
            "emulated_pull_rtt_ms": rtt_s * 1000.0,
            "adaptive_stable_steps": 2,
            "pairing": "back-to-back per rep; verdicts are medians of "
                       "per-rep paired ratios; throughput includes the "
                       "emulated pull RTT on refresh submissions "
                       "(_attach_pull_rtt disclosure), losses are real",
        },
        "tau_arms": arms,
        "frontier": frontier,
        "significance_filter": kkt,
        "divergence_drill": _consistency_divergence_drill(mesh, smoke),
    }


@benchmark("consistency")
def consistency_perf(smoke: bool = False) -> None:
    """`make consistency-bench`: the self-driving consistency A/B.
    Structural contracts assert in every mode (bounded-delay holds per
    arm, the controller widened τ, KKT accounting reconciles against
    ``ps_push_keys_total``, the divergence drill backed off + rolled
    back + re-converged with the episode bundled); the wall-clock
    frontier verdicts (adaptive beats fixed τ=0 on throughput, beats
    fixed τ=max on final loss) assert only on full runs — smoke runs on
    a 2-core CI container where a throughput ordering would be noise."""
    import json as _json
    import os as _os
    import tempfile as _tempfile

    out = consistency_ab(smoke)
    for arm in out["tau_arms"].values():
        assert arm["staleness"]["within_bound"], arm["staleness"]
    ctl = out["tau_arms"]["adaptive"]["controller"]
    assert max(ctl["trace"]) > ctl["trace"][0], (
        f"adaptive controller never widened tau: {ctl['trace']}"
    )
    kkt_on = out["significance_filter"]["on"]
    assert kkt_on["accounting"]["reconciled"], kkt_on
    assert kkt_on["counter_reconciled"], kkt_on
    assert kkt_on["accounting"]["suppressed"] > 0, kkt_on
    drill = out["divergence_drill"]
    assert drill["episodes"] and drill["episodes"][0]["rolled_back"], drill
    assert drill["alert_fired"] and drill["bundle_captured"], drill
    assert drill["reconverged"], drill
    if not smoke:
        assert out["frontier"]["adaptive_beats_tau0_throughput"], (
            out["frontier"]
        )
        assert out["frontier"]["adaptive_beats_taumax_loss"], (
            out["frontier"]
        )
    report(
        "consistency_adaptive_examples_per_s",
        out["tau_arms"]["adaptive"]["examples_per_s_median"],
        "examples/s",
    )
    report(
        "consistency_tau0_examples_per_s",
        out["tau_arms"]["tau0"]["examples_per_s_median"],
        "examples/s",
    )
    report(
        "consistency_adaptive_tau_reached", max(ctl["trace"]), "ministeps"
    )
    report(
        "consistency_kkt_suppressed_keys",
        kkt_on["accounting"]["suppressed"],
        "keys",
    )
    report(
        "consistency_drill_rollbacks", len(drill["episodes"]), "episodes"
    )
    out_path = _os.environ.get("PS_CONSISTENCY_OUT") or _os.path.join(
        _tempfile.gettempdir(), "ps_consistency.json"
    )
    with open(out_path, "w") as f:
        _json.dump({"consistency_record": out}, f, indent=2)
