"""Per-component performance tests.

Counterpart of the reference's perf binaries in ``src/test/``:
``kv_vector_perf_ps.cc``, ``kv_map_perf_ps.cc``, ``kv_layer_perf_ps.cc``,
``network_perf_ps.cc``, ``sparse_matrix_perf.cc``. Each module times one
subsystem on the live backend (the real chip, or a virtual CPU mesh under
``JAX_PLATFORMS=cpu``) and prints one JSON line per metric:
``{"metric": ..., "value": ..., "unit": ...}``.

Run all:    python -m parameter_server_tpu.benchmarks [--smoke]
Run one:    python -m parameter_server_tpu.benchmarks kv_vector [--smoke]
"""

from __future__ import annotations

import json
import time
from typing import Callable, Dict

REGISTRY: Dict[str, Callable[[bool], None]] = {}

#: chip HBM peak bandwidth (GB/s) by jax device_kind — the roofline
#: denominator for every frac-of-peak field (bench.py roofline_fields,
#: components.ftrl_sparse_ab/ftrl_chain). Unknown kinds (CPU hosts)
#: resolve to None and the frac field is reported as null, not faked.
HBM_PEAK_GB_S = {
    "TPU v4": 1228.0,
    "TPU v5 lite": 819.0,
    "TPU v5e": 819.0,
    "TPU v5": 2765.0,
    "TPU v5p": 2765.0,
    "TPU v6 lite": 1640.0,
    "TPU v6e": 1640.0,
}

#: chip bf16 matmul peak (TFLOP/s) by jax device_kind — the MFU
#: denominator for every flops roofline frac (telemetry/device.py
#: roofline gauges, the bench record's ``device`` section). Same
#: honesty rule as the HBM table: unknown kinds (CPU hosts) resolve to
#: None and the frac is reported as null, never faked.
FLOPS_PEAK_TFLOPS = {
    "TPU v4": 275.0,
    "TPU v5 lite": 394.0,
    "TPU v5e": 394.0,
    "TPU v5": 459.0,
    "TPU v5p": 459.0,
    "TPU v6 lite": 918.0,
    "TPU v6e": 918.0,
}


def benchmark(name: str):
    def deco(fn):
        REGISTRY[name] = fn
        return fn

    return deco


def report(metric: str, value: float, unit: str) -> None:
    print(json.dumps({"metric": metric, "value": round(value, 2), "unit": unit}), flush=True)


def timeit(fn, n: int, warmup: int = 3, budget_s: float = 90.0) -> float:
    """Median of up to 3 windows of up to n calls; returns seconds/call.

    A wall-clock budget bounds the whole measurement: on the tunneled
    backend a single kv push can cost seconds of link time, and the
    un-budgeted 3+3x10 call schedule blew the watcher's suite timeout
    (BENCH_ONCHIP.md 2026-07-30: TIMEOUT after 2400s with half the
    metrics unreported). Fast paths still get the full median-of-3.
    """
    t_start = time.perf_counter()
    fn()  # always warm at least once (compile/transfer caches)
    # estimate per-call cost from a SECOND, post-compile call: the first
    # includes jit compilation (~20-30s on the tunneled chip), which
    # would collapse n_eff to 1 for every jitted fast path
    t1 = time.perf_counter()
    fn()
    per = max(time.perf_counter() - t1, 1e-9)
    for _ in range(warmup - 2):
        if time.perf_counter() - t_start > budget_s / 4:
            break
        fn()
    n_eff = max(1, min(n, int(budget_s / (3 * per)) or 1))
    times = []
    t_meas = time.perf_counter()
    for _ in range(3):
        w0 = time.perf_counter()
        for _ in range(n_eff):
            fn()
        times.append((time.perf_counter() - w0) / n_eff)
        if time.perf_counter() - t_meas > budget_s:
            break
    # lower median: with 2 windows (budget break) this picks the FASTER
    # one — a wedge-spiked window must not become the reported rate
    return sorted(times)[(len(times) - 1) // 2]
