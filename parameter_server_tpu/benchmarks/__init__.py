"""Per-component performance tests.

Counterpart of the reference's perf binaries in ``src/test/``:
``kv_vector_perf_ps.cc``, ``kv_map_perf_ps.cc``, ``kv_layer_perf_ps.cc``,
``network_perf_ps.cc``, ``sparse_matrix_perf.cc``. Each module times one
subsystem on the live backend (the real chip, or a virtual CPU mesh under
``JAX_PLATFORMS=cpu``) and prints one JSON line per metric:
``{"metric": ..., "value": ..., "unit": ...}``.

Run all:    python -m parameter_server_tpu.benchmarks [--smoke]
Run one:    python -m parameter_server_tpu.benchmarks kv_vector [--smoke]
"""

from __future__ import annotations

import json
import time
from typing import Callable, Dict

REGISTRY: Dict[str, Callable[[bool], None]] = {}


def benchmark(name: str):
    def deco(fn):
        REGISTRY[name] = fn
        return fn

    return deco


def report(metric: str, value: float, unit: str) -> None:
    print(json.dumps({"metric": metric, "value": round(value, 2), "unit": unit}), flush=True)


def timeit(fn, n: int, warmup: int = 3) -> float:
    """Median-of-3 windows of n calls; returns seconds per call."""
    for _ in range(warmup):
        fn()
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(n):
            fn()
        times.append((time.perf_counter() - t0) / n)
    return sorted(times)[1]
