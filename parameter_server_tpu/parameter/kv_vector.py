"""KVVector: sharded key-value vectors.

Counterpart of ``src/parameter/kv_vector.h`` (KVVector<K,V>): values are
fixed-length-k arrays per key, multiple isolated channels, push merges by
addition, pull returns the current values. The reference stores ordered
(key, value) arrays per node and matches messages with
parallel_ordered_match; here each channel owns

- a host ``KeyDirectory`` (ordered global keys or hash mapping), and
- a device table ``[P, k]`` sharded over the server mesh axis,

and push/pull are the collective kernels in ``ops/kv_ops.py``. The
``buffer_value`` mode of the reference (stash received data per timestamp
for later merge — used by BCD servers to aggregate worker gradients before
an update) maps to ``pull_buffered``/``buffer``: pushes land in a staging
table instead of the live one.

**Zero-copy contract.** Channel tables (and staging buffers) are updated
IN PLACE: pushes dispatch through ``kv_ops.push_donated``, so no
``[P, k]`` copy is materialized per push. Consequently ``table()`` /
``buffer()`` return live views that the NEXT push to that channel
invalidates (read-after-donate raises) — snapshot paths must copy
first, which ``get_replica``/``write_to_file`` do (host ``np.asarray``)
and ``table(copy=True)`` offers on device. Pull results never alias the
table (gathers materialize fresh rows), so pulled values stay valid
across later pushes. See doc/PERFORMANCE.md "Donation rules".
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import file as psfile

from ..ops import kv_ops
from ..parallel import mesh as meshlib
from ..system.message import Task
from .parameter import KeyDirectory, Parameter, pad_slots


class _Channel:
    def __init__(self, directory: KeyDirectory, table: jax.Array):
        self.directory = directory
        self.table = table
        self.key: Optional[np.ndarray] = None  # last key set (ref data_[chl].key)
        self.buffers: Dict[int, jax.Array] = {}  # ts -> staged pushes


class KVVector(Parameter):
    def __init__(
        self,
        mesh=None,
        k: int = 1,
        num_slots: int = 1 << 20,
        hashed: bool = True,
        dtype=jnp.float32,
        buffer_value: bool = False,
        id: Optional[int] = None,
        name: str = "",
    ):
        super().__init__(id=id, name=name)
        if mesh is None:
            assert self.po.mesh is not None, "Postoffice.start() first"
            mesh = self.po.mesh
        self.mesh = mesh
        self.k = int(k)
        self.dtype = dtype
        self.buffer_value = buffer_value
        # convention (same as KVMap): hashed directories use the
        # CONFIGURED modulus — keys keep their slots across elastic
        # resizes (async_sgd.py's note); exact directories (set_keys)
        # use the PADDED capacity so the miss sentinel lands outside
        # every shard's range
        self.num_slots_config = int(num_slots)
        self.num_slots = pad_slots(num_slots, meshlib.num_servers(mesh))
        self.hashed = hashed
        self._channels: Dict[int, _Channel] = {}

    # -- channel management (ref operator[]/Clear) --

    def channel(self, ch: int = 0) -> _Channel:
        if ch not in self._channels:
            directory = KeyDirectory(
                self.num_slots_config if self.hashed else self.num_slots,
                hashed=self.hashed,
            )
            table = self._zeros()
            self._channels[ch] = _Channel(directory, table)
        return self._channels[ch]

    def __getitem__(self, ch: int) -> _Channel:
        return self.channel(ch)

    def clear(self, ch: int) -> None:
        self._channels.pop(ch, None)

    def _zeros(self) -> jax.Array:
        arr = jnp.zeros((self.num_slots, self.k), self.dtype)
        return jax.device_put(arr, meshlib.table_sharding(self.mesh))

    def set_keys(self, ch: int, keys: np.ndarray) -> None:
        """Install an exact ordered key set for a channel (ref: the worker
        assigns ``model_[ch].key = key`` before pulling).

        The input is sorted and de-duplicated (``np.unique``) before
        install: exact directories look keys up with ``searchsorted``,
        which SILENTLY corrupts the mapping on unsorted/duplicate input
        (the regression this guards: caller-order keys landing in wrong
        slots). The installed, canonical key array is kept on
        ``channel(ch).key``."""
        c = self.channel(ch)
        keys = np.unique(np.asarray(keys, dtype=np.int64))
        c.directory = KeyDirectory(self.num_slots, keys=keys, hashed=False)
        c.key = keys

    # -- push/pull --

    def slots(self, ch: int, keys: np.ndarray) -> jnp.ndarray:
        # signature-cached: a repeated key set skips hash/searchsorted
        # AND the host->device index upload (KeyDirectory slot cache)
        return self.channel(ch).directory.slots_device(keys)

    def pull(
        self,
        task: Task,
        keys: Optional[np.ndarray] = None,
        slots: Optional[jax.Array] = None,
        callback=None,
    ) -> int:
        """Async pull; returns the timestamp. Result via ``wait_pull``."""
        ch = task.key_channel
        c = self.channel(ch)
        if slots is None:
            assert keys is not None
            c.key = np.asarray(keys, dtype=np.int64)
            slots = self.slots(ch, keys)

        def step():
            return kv_ops.pull(c.table, slots, mesh=self.mesh, batch_sharded=False)

        return self.instrumented_submit(
            "pull", ch, len(slots), step, task, callback
        )

    def wait_pull(self, ts: int) -> jax.Array:
        return self.executor.pop_result(ts)

    def push(
        self,
        task: Task,
        keys: Optional[np.ndarray] = None,
        values: Optional[jax.Array] = None,
        slots: Optional[jax.Array] = None,
        callback=None,
    ) -> int:
        """Async additive push (gradient aggregation); returns timestamp."""
        ch = task.key_channel
        c = self.channel(ch)
        if slots is None:
            assert keys is not None
            slots = self.slots(ch, keys)
        vals = jnp.asarray(values, self.dtype).reshape(-1, self.k)

        if self.buffer_value and task.time >= 0:
            # stage into a per-timestamp buffer (ref buffer_[timestamp]);
            # the channel owns its staging buffers, so they update in
            # place too (donated) — merge_buffer readers copy on read
            def step():
                buf = c.buffers.get(task.time)
                if buf is None:
                    buf = self._zeros()
                c.buffers[task.time] = kv_ops.push_donated(
                    buf, slots, vals, mesh=self.mesh, batch_sharded=False
                )
                return c.buffers[task.time]

        else:

            def step():
                # in-place: the channel owns its table; the previous
                # table buffer is consumed (zero-copy contract above)
                c.table = kv_ops.push_donated(
                    c.table, slots, vals, mesh=self.mesh, batch_sharded=False
                )
                return c.table

        return self.instrumented_submit(
            "push", ch, len(slots), step, task, callback
        )

    def push_pull(
        self,
        task: Task,
        keys: Optional[np.ndarray] = None,
        values: Optional[jax.Array] = None,
        slots: Optional[jax.Array] = None,
        pull_keys: Optional[np.ndarray] = None,
        callback=None,
    ) -> int:
        """Fused push→pull round trip: aggregate ``values`` into the
        channel table and return the freshly-updated rows in ONE device
        dispatch (the reference server's "aggregate then reply",
        kv_ops.push_pull). ``pull_keys`` defaults to the pushed keys.
        Bit-identical to ``push`` + ``pull``; result via ``wait_pull``.

        Incompatible with buffered staging: a ``buffer_value`` store
        with a timestamped request stages pushes for later merge, while
        the fused round trip applies-and-reads the LIVE table — raising
        here beats silently corrupting the staged aggregation."""
        if self.buffer_value and task.time >= 0:
            raise ValueError(
                "push_pull applies to the live table; a buffer_value "
                "store with task.time >= 0 stages pushes instead — use "
                "push() + buffer()/pull"
            )
        ch = task.key_channel
        c = self.channel(ch)
        if slots is None:
            assert keys is not None
            slots = self.slots(ch, keys)
        pull_slots = (
            None if pull_keys is None else self.slots(ch, pull_keys)
        )
        vals = jnp.asarray(values, self.dtype).reshape(-1, self.k)

        def step():
            c.table, pulled = kv_ops.push_pull_donated(
                c.table, slots, vals, pull_slots,
                mesh=self.mesh, batch_sharded=False,
            )
            return pulled

        return self.instrumented_submit(
            "push_pull", ch, len(slots), step, task, callback
        )

    def snapshot(self, ch: int = 0, callback=None) -> int:
        """Async donation-immune copy of the channel table; returns the
        timestamp (result via ``executor.wait``/``pop_result``).

        The copy runs as a SUBMITTED step, so it serializes with
        in-flight donated pushes in timestamp order — unlike the
        checkpoint path's drain-then-copy (``get_replica``), which is
        only safe once the caller has stopped submitting. This is the
        read-replica refresh primitive (serving/replica.py): training
        keeps streaming donated pushes while the snapshot lands between
        two of them, and the returned buffer is immune to every later
        push."""
        c = self.channel(ch)

        def step():
            return jnp.array(c.table, copy=True)

        # plain submit, NOT instrumented_submit("pull", ...): a
        # full-table copy counted as a num_slots-key pull would swamp
        # ps_pull_keys_total and the pull latency histogram (the
        # background refresher runs this every refresh_s), breaking the
        # documented union_keys-vs-pull_keys dedup comparison. Refresh
        # latency is observed at the call site instead
        # (ps_serve_replica_refresh_seconds).
        return self.submit(step, self.request(channel=ch), callback)

    def buffer(self, ch: int, ts: int) -> Optional[jax.Array]:
        """Staged pushes for a timestamp (ref KVVector::buffer)."""
        return self.channel(ch).buffers.get(ts)

    def clear_buffer(self, ch: int, ts: int) -> None:
        self.channel(ch).buffers.pop(ts, None)

    # -- direct (synchronous) access used by learners/tests --

    def values(self, ch: int, keys: np.ndarray) -> np.ndarray:
        ts = self.pull(self.request(channel=ch), keys=keys)
        return np.asarray(self.wait_pull(ts))

    def table(self, ch: int = 0, copy: bool = False) -> jax.Array:
        """The channel table. Default is the LIVE array — a zero-copy
        view that the next (donated) push to this channel invalidates;
        ``copy=True`` returns a private snapshot that survives pushes
        (the checkpoint-path contract, doc/PERFORMANCE.md)."""
        t = self.channel(ch).table
        return jnp.array(t, copy=True) if copy else t

    def set_table(self, ch: int, table: jax.Array) -> None:
        self.channel(ch).table = table

    # -- replica hooks --

    def get_replica(self) -> dict:
        # drain in-flight pushes (they donate table buffers on the
        # executor thread — a concurrent host read could hit a freshly
        # deleted buffer), then take host COPIES: the snapshot is immune
        # to every later donated push
        self.executor.wait_all(pop=False)
        return {ch: np.asarray(c.table) for ch, c in self._channels.items()}

    def get_replica_consistent(self) -> "tuple[dict, dict]":
        """Tear-free host snapshot THROUGH the executor: one submitted
        ``snapshot`` copy step per channel, so the copy serializes with
        in-flight donated pushes in timestamp order — no drain, no
        quiesce, safe under a live training stream (unlike
        ``get_replica``'s drain-then-copy, which assumes the caller
        stopped submitting). Returns ``(snapshot, barrier)``: barrier
        maps channel → the snapshot step's executor timestamp; every
        push submitted before it (lower ts) is IN the snapshot, every
        later one is not — the replay contract the recovery drill
        exercises (ReplicaManager.backup_consistent)."""
        barrier = {ch: self.snapshot(ch) for ch in list(self._channels)}
        snap = {
            ch: np.asarray(self.executor.wait(ts))
            for ch, ts in barrier.items()
        }
        return snap, barrier

    def set_replica(self, snapshot: dict) -> None:
        for ch, arr in snapshot.items():
            c = self.channel(ch)
            c.table = jax.device_put(
                jnp.asarray(arr), meshlib.table_sharding(self.mesh)
            )

    def write_to_file(self, path: str, ch: int = 0) -> None:
        """Dump nonzero (key, value) pairs as text (ref WriteToFile)."""
        self.executor.wait_all(pop=False)  # donated pushes settle first
        c = self.channel(ch)
        tbl = np.asarray(c.table)
        if c.directory.keys is not None:
            keys = c.directory.keys
            vals = tbl[: len(keys)]
        else:
            keys = np.arange(self.num_slots, dtype=np.int64)
            vals = tbl
        nz = np.any(vals != 0, axis=1)
        with psfile.open_write(path) as f:
            for key, val in zip(keys[nz], vals[nz]):
                f.write(f"{key}\t" + "\t".join(str(x) for x in val) + "\n")
