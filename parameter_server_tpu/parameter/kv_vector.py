"""KVVector: sharded key-value vectors.

Counterpart of ``src/parameter/kv_vector.h`` (KVVector<K,V>): values are
fixed-length-k arrays per key, multiple isolated channels, push merges by
addition, pull returns the current values. The reference stores ordered
(key, value) arrays per node and matches messages with
parallel_ordered_match; here each channel owns

- a host ``KeyDirectory`` (ordered global keys or hash mapping), and
- a device table ``[P, k]`` sharded over the server mesh axis,

and push/pull are the collective kernels in ``ops/kv_ops.py``. The
``buffer_value`` mode of the reference (stash received data per timestamp
for later merge — used by BCD servers to aggregate worker gradients before
an update) maps to ``pull_buffered``/``buffer``: pushes land in a staging
table instead of the live one.

**Zero-copy contract.** Channel tables (and staging buffers) are updated
IN PLACE: pushes dispatch through ``kv_ops.push_donated``, so no
``[P, k]`` copy is materialized per push. Consequently ``table()`` /
``buffer()`` return live views that the NEXT push to that channel
invalidates (read-after-donate raises) — snapshot paths must copy
first, which ``get_replica``/``write_to_file`` do (host ``np.asarray``)
and ``table(copy=True)`` offers on device. Pull results never alias the
table (gathers materialize fresh rows), so pulled values stay valid
across later pushes. See doc/PERFORMANCE.md "Donation rules".
"""
# bit-identical: this module is under the replay bit-identity contract (pslint determinism pass)

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import file as psfile

from ..ops import kv_ops
from ..parallel import mesh as meshlib
from ..parallel import partition as partlib
from ..system.message import Task
from .parameter import KeyDirectory, Parameter, pad_slots


class _Channel:
    def __init__(self, directory: KeyDirectory, table: jax.Array):
        self.directory = directory
        self.table = table
        self.key: Optional[np.ndarray] = None  # last key set (ref data_[chl].key)
        self.buffers: Dict[int, jax.Array] = {}  # ts -> staged pushes
        # -- live-migration state (KVVector.migrate) --
        # remap_lock serializes slot-resolution+submit against a
        # migration's install+directory-flip: every push/pull is
        # atomically either fully-before (old slots, ts < install) or
        # fully-after (new slots, ts > install) the layout change.
        self.remap_lock = threading.Lock()
        #: open push journal while a migration is snapshotting:
        #: (ts, slots, vals) triples; pushes past the snapshot barrier
        #: replay onto the migrated image in ts order
        self.journal: Optional[List[Tuple[int, np.ndarray, np.ndarray]]] = None  # guarded-by: remap_lock
        #: composed base-slot → current-slot permutation (None until the
        #: first migration); snapshots store BASE layout through it
        self.perm: Optional[np.ndarray] = None  # guarded-by: remap_lock
        self.migrations = 0  # guarded-by: remap_lock


class KVVector(Parameter):
    def __init__(
        self,
        mesh=None,
        k: int = 1,
        num_slots: int = 1 << 20,
        hashed: bool = True,
        dtype=jnp.float32,
        buffer_value: bool = False,
        id: Optional[int] = None,
        name: str = "",
    ):
        super().__init__(id=id, name=name)
        if mesh is None:
            assert self.po.mesh is not None, "Postoffice.start() first"
            mesh = self.po.mesh
        self.mesh = mesh
        self.k = int(k)
        self.dtype = dtype
        self.buffer_value = buffer_value
        # convention (same as KVMap): hashed directories use the
        # CONFIGURED modulus — keys keep their slots across elastic
        # resizes (async_sgd.py's note); exact directories (set_keys)
        # use the PADDED capacity so the miss sentinel lands outside
        # every shard's range
        self.num_slots_config = int(num_slots)
        self.num_slots = pad_slots(num_slots, meshlib.num_servers(mesh))
        self.hashed = hashed
        # the table spec resolves ONCE per store through the mesh's
        # declarative partitioner (parallel/partition.py) — no more
        # per-callsite NamedSharding construction
        self.partitioner = partlib.for_mesh(mesh)
        self._table_sharding = self.partitioner.table_sharding()
        self._channels: Dict[int, _Channel] = {}
        # serializes migrations (and consistent snapshots against them)
        self._migration_lock = threading.Lock()
        #: bumped by note_external_restore() BEFORE a recovery install
        #: is submitted; an in-flight migration whose snapshot predates
        #: the bump discards its image and re-snapshots
        self._restore_generation = 0  # guarded-by: _gen_lock
        self._gen_lock = threading.Lock()

    # -- channel management (ref operator[]/Clear) --

    def channel(self, ch: int = 0) -> _Channel:
        if ch not in self._channels:
            directory = KeyDirectory(
                self.num_slots_config if self.hashed else self.num_slots,
                hashed=self.hashed,
            )
            table = self._zeros()
            self._channels[ch] = _Channel(directory, table)
        return self._channels[ch]

    def __getitem__(self, ch: int) -> _Channel:
        return self.channel(ch)

    def clear(self, ch: int) -> None:
        self._channels.pop(ch, None)

    def _zeros(self) -> jax.Array:
        arr = jnp.zeros((self.num_slots, self.k), self.dtype)
        return jax.device_put(arr, self._table_sharding)

    def set_keys(self, ch: int, keys: np.ndarray) -> None:
        """Install an exact ordered key set for a channel (ref: the worker
        assigns ``model_[ch].key = key`` before pulling).

        The input is sorted and de-duplicated (``np.unique``) before
        install: exact directories look keys up with ``searchsorted``,
        which SILENTLY corrupts the mapping on unsorted/duplicate input
        (the regression this guards: caller-order keys landing in wrong
        slots). The installed, canonical key array is kept on
        ``channel(ch).key``."""
        c = self.channel(ch)
        keys = np.unique(np.asarray(keys, dtype=np.int64))
        with c.remap_lock:
            directory = KeyDirectory(self.num_slots, keys=keys, hashed=False)
            if c.perm is not None:
                # a rebuilt directory must keep routing into the
                # migrated layout
                directory.set_remap(c.perm)
            c.directory = directory
            c.key = keys

    # -- push/pull --

    def slots(self, ch: int, keys: np.ndarray) -> jnp.ndarray:
        # signature-cached: a repeated key set skips hash/searchsorted
        # AND the host->device index upload (KeyDirectory slot cache)
        return self.channel(ch).directory.slots_device(keys)

    def pull(
        self,
        task: Task,
        keys: Optional[np.ndarray] = None,
        slots: Optional[jax.Array] = None,
        callback=None,
    ) -> int:
        """Async pull; returns the timestamp. Result via ``wait_pull``."""
        ch = task.key_channel
        c = self.channel(ch)
        # slot-resolution + submit are atomic against a live migration
        # (remap_lock): a pull is either fully-before the layout flip
        # (old slots, runs before the install step) or fully-after —
        # reads stay correct mid-migration, they never error
        with c.remap_lock:
            if slots is None:
                assert keys is not None
                c.key = np.asarray(keys, dtype=np.int64)
                slots = self.slots(ch, keys)
            resolved = slots

            def step():
                return kv_ops.pull(
                    c.table, resolved, mesh=self.mesh, batch_sharded=False
                )

            return self.instrumented_submit(
                "pull", ch, len(resolved), step, task, callback
            )

    def wait_pull(self, ts: int) -> jax.Array:
        return self.executor.pop_result(ts)

    def push(
        self,
        task: Task,
        keys: Optional[np.ndarray] = None,
        values: Optional[jax.Array] = None,
        slots: Optional[jax.Array] = None,
        callback=None,
    ) -> int:
        """Async additive push (gradient aggregation); returns timestamp."""
        ch = task.key_channel
        c = self.channel(ch)
        # atomic against a live migration, same contract as pull(); a
        # push that lands while the migration snapshot is open is also
        # JOURNALED — if its ts is past the snapshot barrier it replays
        # onto the migrated image in ts order (doc/ROBUSTNESS.md)
        with c.remap_lock:
            if slots is None:
                assert keys is not None
                slots = self.slots(ch, keys)
            resolved = slots
            vals = jnp.asarray(values, self.dtype).reshape(-1, self.k)

            if self.buffer_value and task.time >= 0:
                # stage into a per-timestamp buffer (ref
                # buffer_[timestamp]); the channel owns its staging
                # buffers, so they update in place too (donated) —
                # merge_buffer readers copy on read
                def step():
                    buf = c.buffers.get(task.time)
                    if buf is None:
                        buf = self._zeros()
                    c.buffers[task.time] = kv_ops.push_donated(
                        buf, resolved, vals, mesh=self.mesh,
                        batch_sharded=False,
                    )
                    return c.buffers[task.time]

            else:

                def step():
                    # in-place: the channel owns its table; the previous
                    # table buffer is consumed (zero-copy contract above)
                    c.table = kv_ops.push_donated(
                        c.table, resolved, vals, mesh=self.mesh,
                        batch_sharded=False,
                    )
                    return c.table

            ts = self.instrumented_submit(
                "push", ch, len(resolved), step, task, callback
            )
            if c.journal is not None and not (
                self.buffer_value and task.time >= 0
            ):
                c.journal.append(
                    (ts, np.asarray(resolved), np.asarray(vals))
                )
            return ts

    def push_pull(
        self,
        task: Task,
        keys: Optional[np.ndarray] = None,
        values: Optional[jax.Array] = None,
        slots: Optional[jax.Array] = None,
        pull_keys: Optional[np.ndarray] = None,
        callback=None,
    ) -> int:
        """Fused push→pull round trip: aggregate ``values`` into the
        channel table and return the freshly-updated rows in ONE device
        dispatch (the reference server's "aggregate then reply",
        kv_ops.push_pull). ``pull_keys`` defaults to the pushed keys.
        Bit-identical to ``push`` + ``pull``; result via ``wait_pull``.

        Incompatible with buffered staging: a ``buffer_value`` store
        with a timestamped request stages pushes for later merge, while
        the fused round trip applies-and-reads the LIVE table — raising
        here beats silently corrupting the staged aggregation."""
        if self.buffer_value and task.time >= 0:
            raise ValueError(
                "push_pull applies to the live table; a buffer_value "
                "store with task.time >= 0 stages pushes instead — use "
                "push() + buffer()/pull"
            )
        ch = task.key_channel
        c = self.channel(ch)
        with c.remap_lock:  # atomic vs live migration (see push/pull)
            if slots is None:
                assert keys is not None
                slots = self.slots(ch, keys)
            resolved = slots
            pull_slots = (
                None if pull_keys is None else self.slots(ch, pull_keys)
            )
            vals = jnp.asarray(values, self.dtype).reshape(-1, self.k)

            def step():
                c.table, pulled = kv_ops.push_pull_donated(
                    c.table, resolved, vals, pull_slots,
                    mesh=self.mesh, batch_sharded=False,
                )
                return pulled

            ts = self.instrumented_submit(
                "push_pull", ch, len(resolved), step, task, callback
            )
            if c.journal is not None:
                c.journal.append(
                    (ts, np.asarray(resolved), np.asarray(vals))
                )
            return ts

    def snapshot(self, ch: int = 0, callback=None) -> int:
        """Async donation-immune copy of the channel table; returns the
        timestamp (result via ``executor.wait``/``pop_result``).

        The copy runs as a SUBMITTED step, so it serializes with
        in-flight donated pushes in timestamp order — unlike the
        checkpoint path's drain-then-copy (``get_replica``), which is
        only safe once the caller has stopped submitting. This is the
        read-replica refresh primitive (serving/replica.py): training
        keeps streaming donated pushes while the snapshot lands between
        two of them, and the returned buffer is immune to every later
        push."""
        c = self.channel(ch)

        def step():
            return jnp.array(c.table, copy=True)

        # plain submit, NOT instrumented_submit("pull", ...): a
        # full-table copy counted as a num_slots-key pull would swamp
        # ps_pull_keys_total and the pull latency histogram (the
        # background refresher runs this every refresh_s), breaking the
        # documented union_keys-vs-pull_keys dedup comparison. Refresh
        # latency is observed at the call site instead
        # (ps_serve_replica_refresh_seconds).
        return self.submit(step, self.request(channel=ch), callback)

    def buffer(self, ch: int, ts: int) -> Optional[jax.Array]:
        """Staged pushes for a timestamp (ref KVVector::buffer)."""
        return self.channel(ch).buffers.get(ts)

    def clear_buffer(self, ch: int, ts: int) -> None:
        self.channel(ch).buffers.pop(ts, None)

    # -- direct (synchronous) access used by learners/tests --

    def values(self, ch: int, keys: np.ndarray) -> np.ndarray:
        ts = self.pull(self.request(channel=ch), keys=keys)
        return np.asarray(self.wait_pull(ts))

    def table(self, ch: int = 0, copy: bool = False) -> jax.Array:
        """The channel table. Default is the LIVE array — a zero-copy
        view that the next (donated) push to this channel invalidates;
        ``copy=True`` returns a private snapshot that survives pushes
        (the checkpoint-path contract, doc/PERFORMANCE.md)."""
        t = self.channel(ch).table
        return jnp.array(t, copy=True) if copy else t

    def set_table(self, ch: int, table: jax.Array) -> None:
        self.channel(ch).table = table

    # -- live migration (heat-driven repartitioning) --

    def _to_base(self, c: _Channel, arr: np.ndarray) -> np.ndarray:
        """Translate a current-layout host table to BASE (pre-migration)
        slot order. Snapshots/checkpoints are stored base-layout, so a
        backup taken before a migration restores correctly after one
        (set_replica re-applies the live permutation) and bit-parity
        checks compare layout-independent bytes."""
        with c.remap_lock:
            perm = c.perm
        return arr if perm is None else np.asarray(arr)[perm]

    def layout(self, ch: int = 0) -> Optional[np.ndarray]:
        """The channel's composed base→current slot permutation (copy),
        or None while the layout is untouched."""
        c = self.channel(ch)
        with c.remap_lock:
            return None if c.perm is None else c.perm.copy()

    def note_external_restore(self) -> None:
        """MUST be called before submitting a recovery install
        (ReplicaManager.recover does): an in-flight ``migrate`` whose
        snapshot predates this bump discards its stale image and
        re-snapshots, so a recovery landing mid-migration is never
        overwritten by pre-recovery bytes."""
        with self._gen_lock:
            self._restore_generation += 1

    def _generation(self) -> int:
        with self._gen_lock:
            return self._restore_generation

    def _submit_push_locked(self, c: _Channel, ch: int,
                            slots_np: np.ndarray,
                            vals_np: np.ndarray) -> int:  # holds-lock: c.remap_lock
        """Replay one journaled push through the SAME donated push
        kernel (same shapes → same executable → same accumulation
        order: the bit-identity contract)."""
        slots = jnp.asarray(slots_np.astype(np.int32))
        vals = jnp.asarray(vals_np, self.dtype).reshape(-1, self.k)

        def step():
            c.table = kv_ops.push_donated(
                c.table, slots, vals, mesh=self.mesh, batch_sharded=False
            )
            return c.table

        return self.instrumented_submit(
            "push", ch, len(slots_np), step, self.request(channel=ch), None
        )

    def migrate(self, perm: np.ndarray, ch: int = 0,
                max_attempts: int = 5) -> dict:
        """Online slot migration: move rows to the layout ``perm`` (row
        ``j`` → row ``perm[j]``) WITHOUT stopping the push/pull stream.

        Protocol (the PR 9 consistent-snapshot machinery):

        1. open the channel's push journal, then take a submitted
           ``snapshot`` copy — its executor timestamp is the barrier
           that bounds exactly which pushes are in the snapshot;
        2. permute the snapshot on host into the new layout;
        3. under ``remap_lock``: submit the install of the permuted
           image, replay journaled pushes with ts PAST the barrier in
           timestamp order with translated slots, and flip the
           directory remap — every concurrent push/pull is atomically
           fully-before or fully-after the flip (serving degrades to
           lock/queue latency; it never errors).

        A recovery that lands mid-flight bumps the restore generation
        (``note_external_restore``) and the migration re-snapshots —
        recovery wins wholesale, then journal/replay correctness is
        re-established on the retry (tests/test_rebalance.py composes
        the two live). Post-migration state is bit-identical to an
        undisturbed run, compared in base layout.
        """
        from ..system import faults

        perm = np.asarray(perm, dtype=np.int64)
        n = self.num_slots
        if perm.shape != (n,) or not np.array_equal(
            np.sort(perm), np.arange(n)
        ):
            raise ValueError(
                "perm must be a bijection over the padded slot "
                f"capacity ({n})"
            )
        c = self.channel(ch)
        rows_moved = int(np.count_nonzero(perm != np.arange(n)))
        with self._migration_lock:
            attempts = 0
            while True:
                attempts += 1
                if attempts > max_attempts:
                    raise RuntimeError(
                        "migration could not complete: a recovery "
                        f"interleaved {max_attempts} times"
                    )
                gen0 = self._generation()
                with c.remap_lock:
                    c.journal = []
                barrier_ts = self.snapshot(ch)
                snap = np.asarray(self.executor.wait(barrier_ts))
                # fault point: the drill stalls here to widen the
                # copy window / force the kill to land mid-migration
                faults.inject("rebalance.migrate")
                img = np.empty_like(snap)
                img[perm] = snap
                with c.remap_lock:
                    if self._generation() != gen0:
                        c.journal = None
                        continue  # stale image: recovery landed first
                    journal, c.journal = c.journal, None
                    sharded = jax.device_put(
                        jnp.asarray(img), self._table_sharding
                    )

                    def install(t=sharded):
                        c.table = t
                        return c.table

                    install_ts = self.submit(
                        install, self.request(channel=ch)
                    )
                    replayed = 0
                    for ts, slots_np, vals_np in journal:
                        if ts <= barrier_ts:
                            continue  # already inside the snapshot
                        safe = np.minimum(slots_np, n - 1)
                        new_slots = np.where(
                            slots_np < n, perm[safe], slots_np
                        )
                        self._submit_push_locked(c, ch, new_slots, vals_np)
                        replayed += 1
                    c.directory.set_remap(perm)
                    c.perm = (
                        perm.copy() if c.perm is None else perm[c.perm]
                    )
                    c.migrations += 1
                    break
        self.executor.wait_all(pop=False)
        return {
            "barrier_ts": barrier_ts,
            "install_ts": install_ts,
            "journaled": len(journal),
            "replayed": replayed,
            "rows_moved": rows_moved,
            "attempts": attempts,
        }

    # -- replica hooks --

    def get_replica(self) -> dict:
        # drain in-flight pushes (they donate table buffers on the
        # executor thread — a concurrent host read could hit a freshly
        # deleted buffer), then take host COPIES in BASE layout: the
        # snapshot is immune to later donated pushes AND to layout
        # changes (migrations)
        self.executor.wait_all(pop=False)
        return {
            ch: self._to_base(c, np.asarray(c.table))
            for ch, c in self._channels.items()
        }

    def get_replica_consistent(self) -> "tuple[dict, dict]":
        """Tear-free host snapshot THROUGH the executor: one submitted
        ``snapshot`` copy step per channel, so the copy serializes with
        in-flight donated pushes in timestamp order — no drain, no
        quiesce, safe under a live training stream (unlike
        ``get_replica``'s drain-then-copy, which assumes the caller
        stopped submitting). Returns ``(snapshot, barrier)``: barrier
        maps channel → the snapshot step's executor timestamp; every
        push submitted before it (lower ts) is IN the snapshot, every
        later one is not — the replay contract the recovery drill
        exercises (ReplicaManager.backup_consistent). Holding the
        migration lock keeps the copy and its base-layout translation
        on ONE layout; snapshots are stored layout-independent."""
        with self._migration_lock:
            barrier = {ch: self.snapshot(ch) for ch in list(self._channels)}
            snap = {
                ch: self._to_base(
                    self._channels[ch], np.asarray(self.executor.wait(ts))
                )
                for ch, ts in barrier.items()
            }
        return snap, barrier

    def set_replica(self, snapshot: dict) -> None:
        for ch, arr in snapshot.items():
            c = self.channel(ch)
            arr = np.asarray(arr)
            with c.remap_lock:
                perm = c.perm
            if perm is not None:
                # snapshots are base-layout; re-apply the live layout
                cur = np.empty_like(arr)
                cur[perm] = arr
                arr = cur
            c.table = jax.device_put(jnp.asarray(arr), self._table_sharding)

    def write_to_file(self, path: str, ch: int = 0) -> None:
        """Dump nonzero (key, value) pairs as text (ref WriteToFile)."""
        self.executor.wait_all(pop=False)  # donated pushes settle first
        c = self.channel(ch)
        # base layout: exact-directory key order must line up with rows
        # even after a migration moved them
        tbl = self._to_base(c, np.asarray(c.table))
        if c.directory.keys is not None:
            keys = c.directory.keys
            vals = tbl[: len(keys)]
        else:
            keys = np.arange(self.num_slots, dtype=np.int64)
            vals = tbl
        nz = np.any(vals != 0, axis=1)
        with psfile.open_write(path) as f:
            for key, val in zip(keys[nz], vals[nz]):
                f.write(f"{key}\t" + "\t".join(str(x) for x in val) + "\n")
