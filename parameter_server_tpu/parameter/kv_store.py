"""KVStore: unified façade over the KV containers.

The reference's ``src/parameter/kv_store.h`` is an unfinished placeholder
(its include line even has typos). For capability parity we provide the
obvious unification: a factory returning the right container, so user code
can say ``kv_store(kind=...)`` — and re-export the concrete classes.
"""

from __future__ import annotations

from .kv_layer import KVLayer
from .kv_map import AddEntry, AssignEntry, KVMap
from .kv_vector import KVVector

__all__ = ["KVVector", "KVMap", "KVLayer", "AssignEntry", "AddEntry", "kv_store"]


def kv_store(kind: str = "vector", **kwargs):
    if kind == "vector":
        return KVVector(**kwargs)
    if kind == "map":
        return KVMap(**kwargs)
    if kind == "layer":
        return KVLayer(**kwargs)
    raise ValueError(f"unknown kv store kind: {kind}")
