"""KVLayer: named dense parameter blobs for neural-net workers.

Counterpart of ``src/parameter/kv_layer.h``: layers are keyed by an int/name,
pushed and pulled whole. The reference slices a layer across servers when its
size exceeds ``partition_thr`` and runs a user ``Updater`` on the server
side; small layers live on one server.

TPU-native: each layer is a jax array; layers ≥ ``partition_thr`` elements
are sharded over the server axis (first divisible dim), small ones are
replicated. Push = cross-worker psum of gradients + Updater application
(one fused jitted step); pull = return the (already resident) array.
``zero_copy`` parity: device buffers are donated through the updater so no
copy is made.

**Donation contract** (``donate=True``, the default): the store owns its
layer arrays, so the updater runs IN PLACE — each push consumes the
previous weight buffer instead of materializing a same-sized copy. A
pulled layer is therefore a zero-copy view valid until the NEXT push to
that key (after which reading it raises — jax read-after-donate);
callers that must hold weights across pushes copy them, and
``get_replica`` snapshots to host before any later push can land.
``donate=False`` restores the seed's copying behavior (pull results
stay valid forever at one full-layer HBM copy per push). See
doc/PERFORMANCE.md "Donation rules".
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from jax.sharding import NamedSharding

from ..parallel import partition as partlib
from ..system.message import Task
from .parameter import Parameter


class SGDUpdater:
    """Default updater: w -= lr * grad (ref KVLayerUpdater is a no-op shell;
    CXXNET plugs its optimizer — this is the minimal real one)."""

    def __init__(self, lr: float = 0.01):
        self.lr = lr

    def init(self, name: str, shape, dtype=jnp.float32) -> jax.Array:
        return jnp.zeros(shape, dtype)

    def update(self, name: str, weight: jax.Array, recv: jax.Array) -> jax.Array:
        return weight - self.lr * recv


class KVLayer(Parameter):
    def __init__(
        self,
        partition_thr: int = 1000,
        updater=None,
        mesh=None,
        donate: bool = True,
        id: Optional[int] = None,
        name: str = "",
    ):
        super().__init__(id=id, name=name)
        if mesh is None:
            assert self.po.mesh is not None, "Postoffice.start() first"
            mesh = self.po.mesh
        self.mesh = mesh
        self.partition_thr = int(partition_thr)
        self.updater = updater or SGDUpdater()
        self.donate = bool(donate)
        # placement policy lives in the declarative partitioner now
        # (parallel/partition.py layer_sharding) — resolved once per mesh
        self.partitioner = partlib.for_mesh(mesh)
        self.layers: Dict[object, jax.Array] = {}
        self._update_fns: Dict[object, Callable] = {}

    def _sharding(self, shape) -> NamedSharding:
        return self.partitioner.layer_sharding(shape, self.partition_thr)

    def init_layer(self, key, shape, dtype=jnp.float32) -> jax.Array:
        arr = self.updater.init(key, shape, dtype)
        self.layers[key] = jax.device_put(arr, self._sharding(shape))
        return self.layers[key]

    def __getitem__(self, key) -> jax.Array:
        return self.layers[key]

    def layer(self, key) -> jax.Array:
        return self.layers[key]

    def _update_fn(self, key):
        if key not in self._update_fns:
            updater = self.updater

            def fn(weight, recv):
                return updater.update(key, weight, recv)

            if self.donate:
                # in-place updater: the store owns the weight buffer and
                # replaces it, so donation is legal — a previously pulled
                # view of THIS layer dies with the push (module contract)
                self._update_fns[key] = jax.jit(fn, donate_argnums=(0,))
            else:
                # no-donate: copying mode — pull futures must outlive
                # pushes (donate=False construction)
                self._update_fns[key] = jax.jit(fn)
        return self._update_fns[key]

    def _push_step(self, key, data):
        """The one update-step body both push and push_pull submit:
        donated-push accounting, receive, updater apply, reinstall."""

        def step():
            if self.donate:
                from ..telemetry.instruments import cached_kvops_instruments

                tel = cached_kvops_instruments()
                if tel is not None:
                    tel["donated_pushes"].inc()
            recv = jnp.asarray(data)
            self.layers[key] = self._update_fn(key)(self.layers[key], recv)
            return self.layers[key]

        return step

    def push(self, task: Task, key, data: jax.Array, zero_copy: bool = False, callback=None) -> int:
        """Push a gradient/update for a layer; the updater runs server-side
        (ref KVLayer::Push → SetValue → updater_->Update)."""
        if key not in self.layers:
            self.init_layer(key, data.shape, data.dtype)
        # layers are whole-tensor channels: key-count 1 per request, the
        # layer name as the channel label
        return self.instrumented_submit(
            "push", key, 1, self._push_step(key, data), task, callback
        )

    def pull(self, task: Task, key, callback=None) -> int:
        """Pull the layer (ref KVLayer::Pull; data lands in layer_ / user buf).
        Under ``donate=True`` the result is a zero-copy view valid until
        the next push to ``key`` (module docstring)."""

        def step():
            return self.layers[key]

        return self.instrumented_submit("pull", key, 1, step, task, callback)

    def push_pull(self, task: Task, key, data: jax.Array, callback=None) -> int:
        """Fused push→pull: apply the updater and hand back the freshly
        updated layer in ONE submitted step — the reference server's
        "aggregate then reply" round trip without a second executor
        round trip (used by the nn trainer's parameter refresh). Result
        via ``wait_pull``; bit-identical to ``push`` then ``pull``.
        Accounted under ``ps_push_pull_*`` (store level) only — a layer
        pull returns the resident array, so no extra device launch is
        saved and the kv_ops fused-dispatch histogram stays honest."""
        if key not in self.layers:
            self.init_layer(key, data.shape, data.dtype)
        return self.instrumented_submit(
            "push_pull", key, 1, self._push_step(key, data), task, callback
        )

    def wait_pull(self, ts: int):
        return self.executor.pop_result(ts)

    def get_replica(self) -> dict:
        # drain in-flight (donated) pushes, then host copies
        self.executor.wait_all(pop=False)
        return {k: np.asarray(v) for k, v in self.layers.items()}

    def set_replica(self, snapshot: dict) -> None:
        for k, arr in snapshot.items():
            self.layers[k] = jax.device_put(
                jnp.asarray(arr), self._sharding(arr.shape)
            )
