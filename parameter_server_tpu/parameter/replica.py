"""Replication, checkpointing and recovery.

Counterpart of the reference's replica protocol
(``src/parameter/parameter.h`` SetReplica/GetReplica/Recover — a new server
fetches the dead server's key segment from its replica node) and the
``save_model_every_n_iter`` checkpointing. On TPU the durable store is a
checkpoint directory: sharded tables and learner state are saved with
orbax (resharding on restore handles server-count changes, the analog of
key-range reassignment in ``reassign_server_key_range_ps.cc``), with a
NumPy fallback writer for environments without orbax.
"""
# bit-identical: this module is under the replay bit-identity contract (pslint determinism pass)

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from ..system import faults

_LOG = logging.getLogger(__name__)


def _to_host(tree: Any) -> Any:
    return jax.tree.map(lambda x: np.asarray(x), tree)


def _to_host_copy(tree: Any) -> Any:
    """Owned host copies. np.asarray is a zero-copy passthrough for
    numpy leaves (and can view CPU-backend jax buffers), which is fine
    when the write completes before returning (sync save) but NOT when
    a background thread will serialize the buffer while the caller
    mutates or donates it — the async path must own its snapshot."""
    return jax.tree.map(lambda x: np.array(x), tree)


class Checkpointable:
    """Durable checkpoint/restore mixin over the state_host hook pair.

    Anything exposing ``state_host()`` / ``load_state_host(snapshot)``
    (the same hooks ElasticCoordinator uses for live migration) inherits
    the save/latest/restore-with-template flow from here — its SINGLE
    home, shared by ISGDCompNode (and through it every linear/FM/DeepCTR
    worker) and NNTrainer."""

    def checkpoint(self, manager: "CheckpointManager", step: int) -> str:
        """Durably save the full ``state_host`` snapshot."""
        return manager.save(step, self.state_host())

    def checkpoint_async(self, manager: "CheckpointManager", step: int) -> str:
        """Non-blocking save of the ``state_host`` snapshot (an owned
        copy is taken before returning — see
        :meth:`CheckpointManager.save_async`); training continues while
        the disk write runs. Call ``manager.wait()`` before exit."""
        return manager.save_async(step, self.state_host())

    def restore(self, manager: "CheckpointManager", step: Optional[int] = None) -> int:
        """Restore from the latest (or given) checkpoint; placement goes
        through ``load_state_host`` so every leaf lands back under its
        proper sharding."""
        if step is None:
            step = manager.latest_step()
            assert step is not None, "no checkpoint found"
        self.load_state_host(manager.restore(step, like=self.state_host()))
        return step


def _rebuild_like(tmpl, data, path: str):
    """Rebuild ``data`` (orbax's plain containers: namedtuples as dicts
    keyed by field name, tuples as lists) into ``tmpl``'s structure,
    matching namedtuple fields by NAME. Leaves pass through unchecked."""
    if isinstance(tmpl, tuple) and hasattr(tmpl, "_fields"):  # namedtuple
        if not tmpl._fields:  # e.g. optax EmptyState: orbax stores None
            return type(tmpl)()
        if not isinstance(data, dict):
            # older orbax / roundtripped namedtuple: positional fallback
            data = dict(zip(tmpl._fields, data or ()))
        missing = [f for f in tmpl._fields if f not in data]
        extra = [f for f in data if f not in tmpl._fields]
        if missing or extra:
            raise ValueError(
                f"checkpoint at {path} mismatches {type(tmpl).__name__}: "
                f"missing fields {missing}, unexpected {extra} — saved "
                "with a different optimizer config?"
            )
        return type(tmpl)(
            **{f: _rebuild_like(getattr(tmpl, f), data[f], path)
               for f in tmpl._fields}
        )
    if isinstance(tmpl, dict):
        missing = [k for k in tmpl if k not in data]
        extra = [k for k in data if k not in tmpl]
        if missing or extra:
            raise ValueError(
                f"checkpoint at {path} mismatches the template: missing "
                f"keys {missing}, unexpected {extra} — saved with a "
                "different model/optimizer config?"
            )
        return {k: _rebuild_like(v, data[k], path) for k, v in tmpl.items()}
    if isinstance(tmpl, (list, tuple)):
        if len(tmpl) != len(data):
            raise ValueError(
                f"checkpoint at {path} has {len(data)} entries where the "
                f"template expects {len(tmpl)}"
            )
        return type(tmpl)(
            _rebuild_like(t, d, path) for t, d in zip(tmpl, data)
        )
    return data  # leaf


class CheckpointManager:
    """Save/restore pytrees of (possibly sharded) arrays."""

    def __init__(self, directory: str, use_orbax: bool = True):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._orbax = None
        self._pending: Optional[threading.Thread] = None
        self._async_error: Optional[BaseException] = None
        if use_orbax:
            try:
                import orbax.checkpoint as ocp

                self._orbax = ocp
            except Exception:  # orbax unavailable/broken: fall back to npz
                self._orbax = None

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step:010d}")

    def _write(self, path: str, host_tree: Any) -> None:
        # write under a .tmp name, then atomically rename: a crash (or
        # a daemon writer thread killed at interpreter exit) can only
        # leave a step_*.tmp dir, which latest_step's int() parse skips
        # — never a half-written dir that a later --resume would pick
        tmp = path + ".tmp"
        if os.path.exists(tmp):
            import shutil

            shutil.rmtree(tmp)
        if self._orbax is not None:
            ckptr = self._orbax.PyTreeCheckpointer()
            ckptr.save(tmp, host_tree, force=True)
        else:
            os.makedirs(tmp, exist_ok=True)
            flat, treedef = jax.tree.flatten(host_tree)
            np.savez(
                os.path.join(tmp, "arrays.npz"),
                *flat,
                __treedef__=np.frombuffer(repr(treedef).encode(), dtype=np.uint8),
            )
        # fault point (doc/ROBUSTNESS.md): die mid-write — INSIDE the
        # crash window the tmp-then-rename protocol exists for (tmp dir
        # fully written, final rename never happens). The torn step_*.tmp
        # must never surface from latest_step(), the next wait() must
        # re-raise for async saves, and a subsequent save must heal by
        # rewriting the tmp (tests/test_faults.py pins all three).
        faults.inject("checkpoint.write", detail=path)
        if os.path.exists(path):
            import shutil

            shutil.rmtree(path)
        os.rename(tmp, path)

    def save(self, step: int, tree: Dict[str, Any]) -> str:
        self.wait()  # serialize behind any in-flight async save
        path = self._step_dir(step)
        self._write(path, _to_host(tree))
        return path

    def save_async(self, step: int, tree: Dict[str, Any]) -> str:
        """Non-blocking save: the device→host snapshot happens NOW
        (synchronously — safe under buffer donation, since the caller's
        arrays may be consumed by the very next step), then the disk
        write runs on a background thread while training continues.
        Saves serialize: a new save (sync or async) first drains the
        previous one. A failed background write re-raises from the next
        ``save``/``save_async``/``wait`` call — call :meth:`wait` after
        the training loop so the last checkpoint is durable before the
        process exits. Ref save_model_every_n_iter semantics; overlap
        is the TPU-side improvement (the reference's SaveModel blocks
        its server loop)."""
        self.wait()
        path = self._step_dir(step)
        host_tree = _to_host_copy(tree)  # owned snapshot, synchronous
        t = threading.Thread(
            target=self._write_guarded, args=(path, host_tree),
            name=f"ckpt-save-{step}", daemon=True,
        )
        self._pending = t
        t.start()
        return path

    def _write_guarded(self, path: str, host_tree: Any) -> None:
        try:
            self._write(path, host_tree)
        except BaseException as e:  # surfaced by the next wait()
            self._async_error = e

    def wait(self) -> None:
        """Drain the in-flight async save, re-raising its failure."""
        t, self._pending = self._pending, None
        if t is not None:
            t.join()
        if self._async_error is not None:
            e, self._async_error = self._async_error, None
            raise RuntimeError(
                "async checkpoint save failed (the checkpoint at the "
                "failed step is incomplete on disk)"
            ) from e

    def restore(self, step: int, like: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        self.wait()  # an in-flight async save may be writing this step
        path = self._step_dir(step)
        if self._orbax is not None:
            ckptr = self._orbax.PyTreeCheckpointer()
            out = ckptr.restore(path)
            if like is not None:
                # orbax returns PLAIN containers: namedtuples come back
                # as dicts keyed by FIELD NAME, tuples as lists. Rebuild
                # the template's structure by walking both trees and
                # matching namedtuple fields BY NAME — a sorted-leaf
                # reorder would silently mispair states whose field
                # order differs from sorted order (optax MultiStepsState:
                # mini_step/gradient_step/inner_opt_state/... sorts to
                # acc_grads first, which cross-wired adam moments with
                # accumulator slots before this walk existed).
                # Leaf SHAPES are deliberately not compared: restoring
                # onto a different server count legitimately changes the
                # padded table shapes (the reshard path; callers like
                # load_state_host re-fit rows afterwards).
                out = _rebuild_like(like, out, path)
        else:
            data = np.load(os.path.join(path, "arrays.npz"))
            arrays = [data[k] for k in data.files if k != "__treedef__"]
            assert like is not None, "npz fallback restore needs a template"
            treedef = jax.tree.structure(like)
            if treedef.num_leaves != len(arrays):
                # the orbax path raises a field-named mismatch via
                # _rebuild_like; the npz path must be as loud — a bare
                # unflatten error (or worse, a silent mispairing when
                # counts happen to agree structurally) would point at
                # jax internals instead of the config drift that
                # caused it
                raise ValueError(
                    f"checkpoint at {path} holds {len(arrays)} arrays "
                    f"where the template expects {treedef.num_leaves} "
                    "leaves — saved with a different model/optimizer "
                    "config?"
                )
            out = jax.tree.unflatten(treedef, arrays)
        if like is not None:
            # reshard onto the template's placements (server-count changes OK)
            out = jax.tree.map(
                lambda tmpl, arr: jax.device_put(np.asarray(arr), tmpl.sharding)
                if hasattr(tmpl, "sharding")
                else np.asarray(arr),
                like,
                out,
            )
        return out

    def latest_step(self) -> Optional[int]:
        self.wait()  # a half-written async step dir must not be listed
        steps = []
        # pslint: disable=determinism — feeds max() below, an order-insensitive consumer; sorting the listing would buy nothing
        for name in os.listdir(self.directory):
            if name.startswith("step_"):
                try:
                    steps.append(int(name[5:]))
                except ValueError:
                    pass
        return max(steps) if steps else None


class ReplicaManager:
    """In-memory replica protocol parity (ref kReplicaGroup / kOwnerGroup):
    each Parameter's shard snapshot is mirrored so a replacement node can
    Recover() it — here snapshots are host copies keyed by customer name.

    Two backup paths:

    - :meth:`backup` — the manual drain-then-copy (``get_replica``),
      only safe once the caller has quiesced its own submissions;
    - :meth:`backup_consistent` — snapshots THROUGH the store executor
      (``get_replica_consistent``: one submitted copy step per channel),
      so a LIVE training stream of donated pushes cannot tear it, and
      the returned **barrier** timestamps say exactly which pushes are
      inside the snapshot (every step with a lower executor timestamp)
      — the replay contract the recovery drill's zero-lost-acked-updates
      check rests on (doc/ROBUSTNESS.md "The backup barrier").

    :meth:`start_periodic` runs ``backup_consistent`` on a background
    thread so a crash loses at most one interval of updates instead of
    everything since the last hand-invoked snapshot. Thread safety:
    every map below is guarded (the periodic thread races ``recover()``
    called from the recovery coordinator's poll thread); snapshot I/O
    runs OUTSIDE the lock so a slow store never blocks a concurrent
    recover of a different parameter.
    """

    def __init__(self) -> None:
        self._replicas: Dict[str, dict] = {}  # guarded-by: _lock
        #: per-name snapshot metadata: {"barrier": {ch: ts}, "version",
        #: "at" (wall clock), "consistent" (which path took it)}
        self._meta: Dict[str, dict] = {}  # guarded-by: _lock
        self._periodic: Dict[str, Tuple[threading.Thread, threading.Event]] = {}  # guarded-by: _lock
        self._lock = threading.Lock()

    def _store(self, name: str, snap: dict, barrier: Dict[int, int],
               consistent: bool) -> None:
        with self._lock:
            self._replicas[name] = snap
            prev = self._meta.get(name)
            self._meta[name] = {
                "barrier": dict(barrier),
                "version": (prev["version"] + 1) if prev else 1,
                # pslint: disable=determinism — operator-facing snapshot metadata ('when was this taken'), not part of the replayed/recovered bytes
                "at": time.time(),
                "consistent": consistent,
            }

    def backup(self, parameter) -> None:
        """Manual snapshot via ``get_replica`` (drains the executor,
        then copies — the caller must not be submitting concurrently)."""
        self._store(parameter.name, parameter.get_replica(), {}, False)

    def backup_consistent(self, parameter) -> dict:
        """Tear-free snapshot through the store executor; returns the
        stored metadata (incl. the per-channel barrier timestamps).
        Safe under a concurrent donated-push stream."""
        snap, barrier = parameter.get_replica_consistent()
        self._store(parameter.name, snap, barrier, True)
        return self.meta(parameter.name)

    def recover(self, parameter, through_executor: bool = False,
                timeout: Optional[float] = 60.0) -> bool:
        """Install the last snapshot. ``through_executor`` submits the
        install as a store step so it serializes with in-flight pushes
        in timestamp order (the live-crash path: survivors may still be
        pushing); default installs directly (the quiesced path the
        existing callers assume). The executor wait is BOUNDED
        (``timeout``, None = wait forever): this path runs on the
        recovery coordinator's thread, and a store executor wedged by
        the very failure being recovered must surface a diagnostic
        DeadlineExceeded to the handler machinery — not hang the
        coordinator so no other dead node ever recovers."""
        with self._lock:
            snap = self._replicas.get(parameter.name)
        if snap is None:
            return False
        if through_executor and hasattr(parameter, "submit"):
            # an in-flight live migration (KVVector.migrate) must learn
            # BEFORE this install is submitted that its snapshot is
            # stale — recovery wins wholesale, the migration re-snapshots
            if hasattr(parameter, "note_external_restore"):
                parameter.note_external_restore()
            ts = parameter.submit(
                lambda: parameter.recover(snap),
                parameter.request(),
            )
            parameter.executor.wait(ts, timeout=timeout)
        else:
            parameter.recover(snap)
        return True

    def barrier(self, name: str) -> Dict[int, int]:
        """Per-channel executor timestamps of the last snapshot: a push
        step with a LOWER timestamp is in the snapshot, a higher one is
        not (and must be replayed after a recover)."""
        with self._lock:
            meta = self._meta.get(name)
            return dict(meta["barrier"]) if meta else {}

    def meta(self, name: str) -> Optional[dict]:
        with self._lock:
            m = self._meta.get(name)
            return dict(m) if m else None

    def drop(self, name: str) -> None:
        with self._lock:
            self._replicas.pop(name, None)
            self._meta.pop(name, None)

    # -- the periodic backup loop --

    def start_periodic(self, parameter, interval_s: float = 30.0) -> None:
        """Back up ``parameter`` every ``interval_s`` on a background
        thread (consistent path). One loop per parameter name;
        :meth:`stop_periodic` stops and joins. A failing backup logs
        and retries next tick — the previous good snapshot stays
        installed (never half-replaced: the swap is one guarded dict
        store)."""
        name = parameter.name
        stop = threading.Event()

        def loop() -> None:
            while not stop.wait(interval_s):
                try:
                    self.backup_consistent(parameter)
                except Exception:
                    _LOG.exception(
                        "periodic replica backup of %r failed; keeping "
                        "the previous snapshot and retrying next tick",
                        name,
                    )

        t = threading.Thread(
            target=loop, name=f"replica-backup:{name}", daemon=True
        )
        with self._lock:
            if name in self._periodic:
                raise RuntimeError(
                    f"periodic backup of {name!r} already running"
                )
            self._periodic[name] = (t, stop)
        t.start()

    def stop_periodic(self, name: Optional[str] = None) -> None:
        """Stop (and join) one parameter's backup loop, or all of them."""
        with self._lock:
            if name is None:
                entries = list(self._periodic.items())
                self._periodic.clear()
            else:
                e = self._periodic.pop(name, None)
                entries = [(name, e)] if e else []
        for _, (t, stop) in entries:
            stop.set()
            t.join(timeout=30)
