"""KVMap: sharded key-value store with user-defined entry updaters.

Counterpart of ``src/parameter/kv_map.h`` (KVMap<K,V,E,S>): the reference
applies ``Entry::Set(recv_data, state)`` per key on push and
``Entry::Get(data, state)`` on pull, with a shared mutable ``State``
(learning rate, penalty, progress counters). The TPU inversion: an Entry is
a *vectorized functional updater* over struct-of-arrays state sharded across
the server axis —

    state' = entry.update(state, agg_grads, touched_mask)
    values = entry.get(state)

Push densifies the (idx, grad) request into the owned shard, aggregates
duplicates by addition (the reference receives pre-aggregated worker
messages), and applies the entry update only on touched slots. All shapes
static; the whole update is one fused XLA kernel per shard (VPU,
bandwidth-bound) — this is the server-side compute of the parameter server.
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Protocol

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import file as psfile

from ..utils.compat import shard_map
from jax.sharding import PartitionSpec as P

from ..ops.kv_ops import localize
from ..parallel import mesh as meshlib
from ..parallel import partition as partlib
from ..parallel.mesh import SERVER_AXIS
from ..system.message import Task
from .parameter import KeyDirectory, Parameter, pad_slots


class Entry(Protocol):
    """Vectorized entry semantics (ref kv_map.h KVMapEntry)."""

    def init(self, num_slots: int, k: int) -> dict: ...

    def update(self, state: dict, grad: jnp.ndarray, touched: jnp.ndarray) -> dict: ...

    def get(self, state: dict) -> jnp.ndarray: ...


class AssignEntry:
    """Plain value store: push overwrites, pull reads (default KVMapEntry)."""

    def init(self, num_slots, k):
        return {"value": jnp.zeros((num_slots, k), jnp.float32)}

    def update(self, state, grad, touched):
        return {"value": jnp.where(touched[:, None], grad, state["value"])}

    def get(self, state):
        return state["value"]


class AddEntry:
    """Accumulator: push adds (aggregation server, ref aggregation_ps.cc)."""

    def init(self, num_slots, k):
        return {"value": jnp.zeros((num_slots, k), jnp.float32)}

    def update(self, state, grad, touched):
        return {"value": state["value"] + grad}

    def get(self, state):
        return state["value"]


class KVMap(Parameter):
    def __init__(
        self,
        entry: Entry,
        mesh=None,
        k: int = 1,
        num_slots: int = 1 << 20,
        hashed: bool = True,
        keys: Optional[np.ndarray] = None,
        id: Optional[int] = None,
        name: str = "",
    ):
        super().__init__(id=id, name=name)
        if mesh is None:
            assert self.po.mesh is not None, "Postoffice.start() first"
            mesh = self.po.mesh
        self.mesh = mesh
        self.k = int(k)
        self.entry = entry
        self.num_slots = pad_slots(num_slots, meshlib.num_servers(mesh))
        # convention: HASHED directories use the CONFIGURED modulus (keys
        # keep their slots across elastic resizes — async_sgd.py's note);
        # EXACT directories use the PADDED capacity so the miss sentinel
        # (== capacity) falls outside every shard's range and unknown
        # keys are dropped, not scattered into a padding slot
        is_hashed = keys is None and hashed
        self.directory = KeyDirectory(
            int(num_slots) if is_hashed else self.num_slots,
            keys=keys,
            hashed=is_hashed,
        )
        # resolved ONCE through the mesh's declarative partitioner
        # (parallel/partition.py owns the table spec)
        self.partitioner = partlib.for_mesh(mesh)
        sharding = self.partitioner.table_sharding()
        self.state: Dict[str, jax.Array] = {
            name_: jax.device_put(arr, sharding)
            for name_, arr in entry.init(self.num_slots, self.k).items()
        }
        self._push_fn = self._build_push()

    def _build_push(self):
        n_server = meshlib.num_servers(self.mesh)
        shard = self.num_slots // n_server
        entry = self.entry

        def local(state, ix, v):
            rel, ok = localize(ix, shard)
            g = jnp.zeros((shard, v.shape[-1]), v.dtype)
            g = g.at[rel].add(jnp.where(ok[:, None], v, 0))
            touched = jnp.zeros((shard,), jnp.bool_).at[rel].max(ok)
            new = entry.update(state, g, touched)
            return jax.tree.map(
                lambda n, o: jnp.where(
                    touched.reshape((-1,) + (1,) * (n.ndim - 1)), n, o
                ),
                new,
                state,
            )

        # declared, not hand-built: the updater-state spec tree is
        # the partitioner's one rule (every array leaf row-sharded
        # over the server key ranges)
        state_specs = partlib.state_partition_spec(
            {k_: self.state[k_] for k_ in self.state}
        )

        # the store owns self.state exclusively and replaces it on every
        # push, so the state buffers are donated: the entry update runs
        # in place instead of materializing a fresh struct-of-arrays
        # copy per push (zero-copy contract, doc/PERFORMANCE.md)
        @functools.partial(jax.jit, donate_argnums=(0,))
        def push_fn(state, ix, v):
            return shard_map(
                local,
                mesh=self.mesh,
                in_specs=(state_specs, P(), P()),
                out_specs=state_specs,
            )(state, ix, v)

        return push_fn

    def slots(self, keys: np.ndarray) -> jnp.ndarray:
        # signature-cached host mapping + device upload (KeyDirectory)
        return self.directory.slots_device(keys)

    def push(self, task: Task, keys, values, callback=None) -> int:
        slots = self.slots(keys)
        vals = jnp.asarray(values, jnp.float32).reshape(-1, self.k)

        def step():
            from ..telemetry.instruments import cached_kvops_instruments

            tel = cached_kvops_instruments()
            if tel is not None:
                tel["donated_pushes"].inc()
            self.state = self._push_fn(self.state, slots, vals)
            return self.state

        return self.instrumented_submit(
            "push", task.key_channel, len(slots), step, task, callback
        )

    def pull(self, task: Task, keys, callback=None) -> int:
        slots = self.slots(keys)

        def step():
            from ..ops import kv_ops

            values = self.entry.get(self.state)
            return kv_ops.pull(values, slots, mesh=self.mesh, batch_sharded=False)

        return self.instrumented_submit(
            "pull", task.key_channel, len(slots), step, task, callback
        )

    def wait_pull(self, ts: int) -> jax.Array:
        return self.executor.pop_result(ts)

    def values(self, keys: np.ndarray) -> np.ndarray:
        ts = self.pull(self.request(), keys)
        return np.asarray(self.wait_pull(ts))

    def write_to_file(self, path: str) -> None:
        """Nonzero weights as text (ref KVMap::WriteToFile)."""
        self.executor.wait_all(pop=False)  # donated pushes settle first
        vals = np.asarray(self.entry.get(self.state))
        keys = (
            self.directory.keys
            if self.directory.keys is not None
            else np.arange(self.num_slots)
        )
        vals = vals[: len(keys)]
        nz = np.any(vals != 0, axis=1)
        with psfile.open_write(path) as f:
            for key, val in zip(np.asarray(keys)[nz], vals[nz]):
                f.write(f"{key}\t" + "\t".join(repr(float(x)) for x in val) + "\n")

    def get_replica(self) -> dict:
        # drain in-flight (donated) pushes, then host copies — the
        # snapshot is immune to later in-place updates
        self.executor.wait_all(pop=False)
        return {k_: np.asarray(v) for k_, v in self.state.items()}

    def set_replica(self, snapshot: dict) -> None:
        sharding = self.partitioner.table_sharding()
        self.state = {
            k_: jax.device_put(jnp.asarray(v), sharding) for k_, v in snapshot.items()
        }
