"""Parameter: base class of shared parameters.

Counterpart of ``src/parameter/parameter.{h,cc}``. The reference routes
push/pull messages through Customer/Executor and slices them by server key
range; here the slicing is implicit in the sharded table layout, and the
base class provides: request construction (channel/timestamp/filters/key
range — same fields as ``Parameter::Request`` in parameter.h), the
key directory (global uint64 keys → dense slot ids), and replica hooks.

Key directories come in two modes, both host-side:

- **exact**: a sorted global key array per channel; slot = searchsorted(key)
  (the reference's ordered unique key arrays in kv_vector.h).
- **hashed**: slot = mix64(key) % num_slots — the streaming mode where the
  key universe is unbounded (CTR hashing trick); collisions merge, as in any
  TPU embedding-hash design.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Optional, Sequence

import numpy as np

from ..system.customer import Customer
from ..system.message import INVALID_TIME, FilterSpec, Task
from ..telemetry import registry as telemetry_registry
from ..telemetry.instruments import cached_kvops_instruments as _dir_tel
from ..utils import crc32c
from ..utils.murmur import hash_slots
from ..utils.range import Range


class Parameter(Customer):
    def __init__(self, id: Optional[int] = None, name: str = ""):
        super().__init__(id=id, name=name)
        # push/pull telemetry (doc/OBSERVABILITY.md): latency histograms
        # + key-volume counters per (store, channel); cached here so the
        # request path pays one attribute test when disabled
        self._tel = None
        if telemetry_registry.enabled():
            from ..telemetry.instruments import parameter_instruments

            self._tel = parameter_instruments(
                telemetry_registry.default_registry()
            )

    def instrumented_submit(
        self,
        kind: str,
        channel,
        num_keys: int,
        step,
        task: Optional[Task] = None,
        callback=None,
    ) -> int:
        """Submit a push/pull step with latency + key-count telemetry.

        Latency is submit→finished (queueing + run + materialize — the
        user-visible request latency, ref Parameter::Request round trip),
        observed from the executor's completion callback; ``callback``
        still fires after it. ``kind`` is "push" or "pull"."""
        tel = self._tel
        if tel is None:
            return self.submit(step, task, callback)
        ch = str(channel)
        tel[f"{kind}_keys"].labels(store=self.name, channel=ch).inc(
            max(0, int(num_keys))
        )
        hist = tel[f"{kind}_latency"].labels(store=self.name, channel=ch)
        t0 = time.perf_counter()

        def record_then(cb=callback):
            hist.observe(time.perf_counter() - t0)
            if cb is not None:
                cb()

        return self.submit(step, task, record_then)

    @staticmethod
    def request(
        channel: int = 0,
        ts: int = INVALID_TIME,
        wait: Sequence[int] = (),
        filters: Sequence[FilterSpec] = (),
        key_range: Optional[Range] = None,
    ) -> Task:
        """Build a request task (ref Parameter::Request, parameter.h:24)."""
        return Task(
            request=True,
            time=ts,
            wait_time=list(wait),
            key_channel=channel,
            key_range=key_range if key_range is not None else Range.all(),
            filters=list(filters),
        )

    # -- replica hooks (ref parameter.h SetReplica/GetReplica/Recover) --

    def get_replica(self) -> dict:
        """Snapshot of server-shard state for backup (overridden)."""
        return {}

    def set_replica(self, snapshot: dict) -> None:
        pass

    def get_replica_consistent(self) -> "tuple[dict, dict]":
        """``(snapshot, barrier)`` where the snapshot is safe to take
        under concurrent submissions and ``barrier`` maps channel →
        the executor timestamp the snapshot was taken at (every step
        with a lower timestamp is inside it). Stores with a submitted
        snapshot step override this (KVVector); the base fallback is
        the drain-then-copy ``get_replica`` with no barrier info —
        correct only for quiesced callers, exactly like ``backup()``."""
        return self.get_replica(), {}

    def recover(self, snapshot: dict) -> None:
        self.set_replica(snapshot)


class KeyDirectory:
    """Host-side key → slot mapping for one channel.

    **Exact directories require sorted unique keys**: slot lookup is
    ``np.searchsorted``, which silently mismatches on unsorted input
    (the reference keeps ordered unique key arrays for the same reason,
    kv_vector.h). The constructor raises on violations; callers with
    raw key sets sort+unique first (``KVVector.set_keys`` does).

    **Slot cache** (device analog of the reference's key-caching filter,
    src/filter/key_caching.h): repeated calls with the SAME key array
    skip the hash/searchsorted pass and — via :meth:`slots_device` — the
    host→device index upload. Entries are keyed by the crc32c prefix
    signature the wire filter already uses (utils/crc32c
    .array_signature) and verified exactly against a retained copy of
    the keys (memcmp-speed), so a signature collision can never serve
    wrong slots. LRU over ``CACHE_SLOTS`` entries.
    """

    MAX_SIG_LEN = 2048  # same signature prefix budget as KeyCachingFilter
    CACHE_SLOTS = 8

    def __init__(
        self,
        num_slots: int,
        keys: Optional[np.ndarray] = None,
        hashed: bool = False,
    ):
        self.num_slots = int(num_slots)
        self.hashed = hashed
        self.keys = None if keys is None else np.asarray(keys, dtype=np.int64)
        if self.keys is not None and len(self.keys) > num_slots:
            raise ValueError(f"{len(self.keys)} keys exceed {num_slots} slots")
        if self.keys is not None and len(self.keys) > 1:
            d = np.diff(self.keys)
            if not (d > 0).all():
                kind = "unsorted" if (d < 0).any() else "duplicate"
                raise ValueError(
                    f"exact KeyDirectory requires sorted unique keys "
                    f"({kind} input): searchsorted would silently map "
                    "keys to wrong slots — np.unique the key set first"
                )
        # sig -> [keys_copy, slots, device_slots|None]; MRU at the end.
        # Lock: the parallel ingest pipeline's prep workers call
        # slots() concurrently (learner/ingest.py) — the LRU
        # move_to_end/popitem sequence is not atomic on its own.
        self._slot_cache: "OrderedDict[tuple, list]" = OrderedDict()  # guarded-by: _slot_cache_lock
        self._slot_cache_lock = threading.Lock()
        # composed slot permutation installed by live migrations
        # (KVVector.migrate): computed slots route through it; the miss
        # sentinel (>= len(remap)) passes through untouched
        self._remap: Optional[np.ndarray] = None  # guarded-by: _slot_cache_lock

    def set_remap(self, perm: np.ndarray) -> None:
        """Compose a slot permutation onto the directory (a migration
        moved row ``j`` to ``perm[j]``) and drop the slot cache — its
        entries hold pre-move slots (and their device uploads)."""
        perm = np.asarray(perm, dtype=np.int64)
        with self._slot_cache_lock:
            self._remap = (
                perm.copy() if self._remap is None else perm[self._remap]
            )
            self._slot_cache.clear()

    def _signature(self, keys: np.ndarray) -> tuple:
        return (
            crc32c.array_signature(keys, self.MAX_SIG_LEN),
            keys.shape[0],
            keys.dtype.str,
        )

    def _cache_entry(self, keys: np.ndarray) -> list:
        """Cache row for this key array: ``[keys_copy, slots, device]``.
        Hits verify the full array against the retained copy, so the
        prefix signature only routes — it never decides."""
        sig = self._signature(keys)
        tel = _dir_tel()
        with self._slot_cache_lock:
            entry = self._slot_cache.get(sig)
            if entry is not None and np.array_equal(keys, entry[0]):
                self._slot_cache.move_to_end(sig)
                if tel is not None:
                    tel["slot_cache_hits"].inc()
                return entry
        if tel is not None:
            tel["slot_cache_misses"].inc()
        # compute OUTSIDE the lock: the hash/searchsorted pass is the
        # expensive part, and it must not serialize parallel prep workers
        entry = [np.array(keys, copy=True), self._compute_slots(keys), None]
        with self._slot_cache_lock:
            self._slot_cache[sig] = entry
            self._slot_cache.move_to_end(sig)
            while len(self._slot_cache) > self.CACHE_SLOTS:
                self._slot_cache.popitem(last=False)
        return entry

    def _compute_slots(self, keys: np.ndarray) -> np.ndarray:
        if self.hashed:
            base = hash_slots(keys, self.num_slots)
        else:
            assert self.keys is not None, "exact directory requires keys"
            pos = np.searchsorted(self.keys, keys)
            posc = (
                np.minimum(pos, len(self.keys) - 1) if len(self.keys) else pos
            )
            hit = (
                (pos < len(self.keys)) & (self.keys[posc] == keys)
                if len(self.keys)
                else np.zeros(len(keys), dtype=bool)
            )
            base = np.where(hit, pos, self.num_slots)
        with self._slot_cache_lock:
            remap = self._remap
        if remap is not None:
            # sentinel / out-of-range slots pass through: only rows the
            # migration actually owns get rerouted
            safe = np.minimum(base, len(remap) - 1)
            base = np.where(base < len(remap), remap[safe], base)
        return np.asarray(base, dtype=np.int32)

    def slots(self, keys: np.ndarray) -> np.ndarray:
        """Map global keys to dense int32 slot ids; misses map to the
        sentinel slot ``num_slots`` (dropped by device range masks).
        Cached per key-array signature — treat the result as read-only."""
        return self._cache_entry(np.asarray(keys))[1]

    def slots_device(self, keys: np.ndarray):
        """:meth:`slots` as a device array, cached: a repeated key set
        skips the host→device index upload too (jnp.asarray is the
        transfer the pull/push request path pays per call otherwise)."""
        import jax.numpy as jnp

        entry = self._cache_entry(np.asarray(keys))
        if entry[2] is None:
            entry[2] = jnp.asarray(entry[1])
        return entry[2]


def pad_slots(num_slots: int, num_shards: int) -> int:
    """Round slots up so every server shard is equal-sized (static shapes)."""
    per = -(-num_slots // num_shards)
    return per * num_shards
