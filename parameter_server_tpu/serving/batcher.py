"""Continuous batching for the decode lane (Orca-style iteration-level
scheduling over ONE speculative-decode call).

PR 6 gave decode a dedicated worker, but each `DecodeRequest` still ran
its own `speculative_generate` — one session per call, the chip idle at
batch 1 while the kv2 capture shows decode sustaining its best HBM
bandwidth at batch 8. The batcher turns per-session latency hardware
into fleet throughput hardware: concurrent sessions share one
device-resident :class:`~..models.speculative.SpecBatchState`, joining
at ROUND boundaries into free batch slots and retiring between rounds
without stalling the rest. The batched-matmul weights-read-once
property speculative.py documents is exactly what cross-session
batching amortizes — the target model's verify pass reads its weights
once per round for the whole batch instead of once per session.

Slot lifecycle (doc/SERVING.md "Continuous batching"):

    free ──admit()── prefill+join (one _spec_join_many_jit per WAVE)
      ▲                   │
      │                   ▼
    retire ◄──────── live rounds (_spec_round_jit, whole batch)
    (committed >= limit, or EOS commit: slot freed between rounds)

Threading contract (the PR 3 stateless-or-feeder rule): the batcher is
SINGLE-OWNER — exactly one scheduler thread (the frontend's decode
worker, running :meth:`ServeFrontend._batch_loop`) may call
``admit``/``step``; the owner is recorded on first use and enforced.
Cross-thread visibility is limited to :meth:`stats`, whose mirror
counters are the only shared mutable state and sit behind ``_lock``
(guarded-by annotations checked by pslint's ``locks`` pass).

Correctness contract: GREEDY token parity — every session's output is
token-for-token identical to its own sequential
``speculative_generate(temperature=None)`` run (the greedy variant is
itself pinned equal to plain greedy target decoding), regardless of
who shared the batch or when they joined/left. Pinned by
tests/test_batcher.py under join/leave churn.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import List, Optional, Tuple

import numpy as np

from ..models.speculative import (
    SpecBatchState,
    _spec_join_many_jit,
    _spec_round_block_jit,
    _spec_round_jit,
    spec_batch_alloc,
)


@dataclasses.dataclass
class BatcherConfig:
    """Capacity knobs — all STATIC (they size the compiled state).

    ``slots`` bounds concurrent sessions (occupancy-vs-latency knob:
    more slots amortize the target pass further but add per-round work
    that every resident session waits on — doc/SERVING.md quantifies).
    ``max_prompt`` is the fixed prefill width every joining prompt is
    right-padded to, so joins at any slot share one compilation;
    ``max_new`` bounds per-session ``steps``; ``gamma`` is the shared
    speculation depth (one batch, one draft schedule)."""

    slots: int = 8
    max_prompt: int = 64
    max_new: int = 64
    gamma: int = 4
    # max rounds fused per dispatch by step_block() — a throughput
    # knob, not a correctness one: blocks never overshoot a retirement
    # (K is additionally bounded so no row can hit its limit inside
    # the block) but they DO defer joins to block boundaries, so
    # larger blocks trade admission latency for per-round overhead
    max_block: int = 8

    def capacity(self) -> int:
        # speculation can overshoot a row's budget by gamma, plus the
        # trash slot masked commits land in (same slack as _spec_jit)
        return self.max_prompt + self.max_new + self.gamma + 1


class _Session:
    """One prompt row resident in one slot."""

    __slots__ = ("handle", "row_idx", "slot", "length", "steps", "width",
                 "limit")

    def __init__(self, handle, row_idx, slot, length, steps, width):
        self.handle = handle
        self.row_idx = row_idx
        self.slot = slot
        self.length = length
        self.steps = steps
        self.width = width  # the request's ORIGINAL prompt width
        self.limit = length + steps  # host mirror of the device clock


class BatchHandle:
    """One admitted DecodeRequest: its rows decode as independent
    sessions; the handle completes (is returned from :meth:`step`) when
    the LAST row retires, carrying the reassembled ``[B, P+steps]``
    output in original row order."""

    __slots__ = ("req", "context", "rows_left", "out")

    def __init__(self, req, context, n_rows: int, width: int):
        self.req = req
        self.context = context  # caller cookie (the frontend's Ticket)
        self.rows_left = n_rows
        self.out = np.zeros((n_rows, width + int(req.steps)), np.int32)


# owner-thread: scheduler
class ContinuousBatcher:
    """ONE running speculative decode shared by concurrent sessions.

    Greedy-only by construction (the parity contract); both configs
    must share a vocab. The compiled round is built lazily on the
    first admit; ``warmup()`` forces it ahead of traffic.
    """

    def __init__(self, target_params, target_cfg, draft_params, draft_cfg,
                 config: Optional[BatcherConfig] = None):
        self.cfg = config or BatcherConfig()
        if self.cfg.gamma < 1:
            raise ValueError(f"gamma must be >= 1, got {self.cfg.gamma}")
        if self.cfg.max_prompt < 1 or self.cfg.max_new < 1:
            raise ValueError("max_prompt and max_new must be >= 1")
        self.tparams = target_params
        self.tcfg = target_cfg
        self.dparams = draft_params
        self.dcfg = draft_cfg
        # spec_batch_alloc validates the shared-vocab contract
        self.state: SpecBatchState = spec_batch_alloc(
            target_cfg, draft_cfg, self.cfg.slots, self.cfg.capacity()
        )
        # scheduler-thread-only state (single-owner; no lock by design —
        # the feeder rule, enforced via _check_owner)
        self._free: List[int] = list(range(self.cfg.slots))
        self._sessions: dict = {}  # slot -> _Session
        self._owner: Optional[int] = None
        self._lock = threading.Lock()
        # cross-thread stats mirrors (stats() reads them off-thread)
        self._occupancy = 0  # guarded-by: _lock
        self._joins = 0  # guarded-by: _lock
        self._leaves = 0  # guarded-by: _lock
        self._rounds = 0  # guarded-by: _lock
        self._retired = 0  # guarded-by: _lock
        self._accepted = 0  # guarded-by: _lock
        self._proposed = 0  # guarded-by: _lock
        from ..telemetry.instruments import cached_serve_instruments

        self._tel = cached_serve_instruments

    # -- the feeder rule ------------------------------------------------

    def _check_owner(self) -> None:
        me = threading.get_ident()
        if self._owner is None:
            self._owner = me
        elif self._owner != me:
            raise RuntimeError(
                "ContinuousBatcher is single-owner (PR 3 stateless-or-"
                "feeder rule): admit/step must run on the one scheduler "
                "thread that first used it"
            )

    # -- scheduler-thread API -------------------------------------------

    def free_slots(self) -> int:
        self._check_owner()
        return len(self._free)

    def active_sessions(self) -> int:
        self._check_owner()
        return len(self._sessions)

    def warmup(self) -> None:
        """Compile everything ahead of traffic: the round, the fused
        block (zero-length: compiles the loop without running a round),
        and one join per power-of-two wave size — joins pad to pow2
        (see admit_many), so this is every join compilation traffic can
        ever trigger. The warmup joins write dead rows (steps=1 with
        the first token already committed ⇒ committed == limit, so the
        rows never go live and no session maps to them)."""
        self._check_owner()
        import jax.numpy as jnp

        self.state, _, _ = _spec_round_jit(
            self.tparams, self.dparams, self.state,
            tcfg=self.tcfg, dcfg=self.dcfg, gamma=self.cfg.gamma,
        )
        self.state, _, _ = _spec_round_block_jit(
            self.tparams, self.dparams, self.state, jnp.int32(0),
            tcfg=self.tcfg, dcfg=self.dcfg, gamma=self.cfg.gamma,
        )
        if self._sessions:
            return  # joins write slot 0; only safe on an empty batch
        r = 1
        while r <= self.cfg.slots:
            self.state = _spec_join_many_jit(
                self.tparams, self.dparams, self.state,
                jnp.zeros((r, self.cfg.max_prompt), jnp.int32),
                jnp.ones((r,), jnp.int32), jnp.ones((r,), jnp.int32),
                jnp.full((r,), -1, jnp.int32),
                jnp.zeros((r,), jnp.int32),
                tcfg=self.tcfg, dcfg=self.dcfg,
            )
            r *= 2

    def validate(self, req) -> Tuple[np.ndarray, np.ndarray]:
        """Shape/budget checks for one DecodeRequest (raises ValueError;
        runs BEFORE any slot is consumed so a bad request never leaks
        capacity). Returns ``(prompt [B, P] int32, lengths [B])``."""
        prompt = np.asarray(req.prompt, np.int32)
        if prompt.ndim != 2 or prompt.shape[1] < 1:
            raise ValueError(f"prompt must be [B, P>=1], got {prompt.shape}")
        b, p = prompt.shape
        if p > self.cfg.max_prompt:
            raise ValueError(
                f"prompt width {p} > batcher max_prompt "
                f"{self.cfg.max_prompt}"
            )
        if b > self.cfg.slots:
            raise ValueError(
                f"request batch {b} can never fit in {self.cfg.slots} slots"
            )
        steps = int(req.steps)
        if not 1 <= steps <= self.cfg.max_new:
            raise ValueError(
                f"steps must be in [1, max_new={self.cfg.max_new}], "
                f"got {steps}"
            )
        if req.eos_id is not None and not (
            0 <= int(req.eos_id) < self.tcfg.vocab
        ):
            raise ValueError(
                f"eos_id must be in [0, vocab={self.tcfg.vocab}), "
                f"got {req.eos_id}"
            )
        if req.prompt_lengths is None:
            lengths = np.full(b, p, np.int64)
        else:
            lengths = np.asarray(req.prompt_lengths, np.int64).ravel()
            if lengths.shape != (b,):
                raise ValueError(
                    f"prompt_lengths must be [B={b}], got {lengths.shape}"
                )
            if (lengths < 1).any() or (lengths > p).any():
                raise ValueError(
                    f"prompt_lengths must be in [1, {p}]"
                )
        return prompt, lengths

    def admit(self, req, context=None) -> BatchHandle:
        """Join every row of ``req`` into free slots at this round
        boundary. Raises ValueError on a malformed request and
        RuntimeError when the batch lacks the slots (the frontend's
        scheduler checks ``free_slots()`` first; the admission door
        sheds before it ever gets here)."""
        return self.admit_many([(req, context)])[0]

    def admit_many(self, reqs) -> List[BatchHandle]:
        """Join a WAVE of requests — every row of every ``(req,
        context)`` pair — in ONE ``_spec_join_many_jit`` call, so the
        fixed per-call join cost is paid once per round boundary
        instead of once per session. All requests are validated before
        any slot is consumed (a malformed wave never leaks capacity);
        the whole wave must fit the free slots or RuntimeError."""
        self._check_owner()
        import jax.numpy as jnp

        validated = [(req, ctx) + self.validate(req) for req, ctx in reqs]
        total = sum(prompt.shape[0] for _, _, prompt, _ in validated)
        if total == 0:
            return []
        if total > len(self._free):
            raise RuntimeError(
                f"batch full: {total} rows, {len(self._free)} free slots"
            )
        handles: List[BatchHandle] = []
        padded = np.zeros((total, self.cfg.max_prompt), np.int32)
        len_v = np.zeros(total, np.int32)
        steps_v = np.zeros(total, np.int32)
        eos_v = np.zeros(total, np.int32)
        slots_v = np.zeros(total, np.int32)
        row = 0
        for req, ctx, prompt, lengths in validated:
            b, width = prompt.shape
            handle = BatchHandle(req, ctx, b, width)
            handles.append(handle)
            eos = -1 if req.eos_id is None else int(req.eos_id)
            steps = int(req.steps)
            for r in range(b):
                slot = self._free.pop()
                padded[row, :width] = prompt[r]
                len_v[row] = lengths[r]
                steps_v[row] = steps
                eos_v[row] = eos
                slots_v[row] = slot
                self._sessions[slot] = _Session(
                    handle, r, slot, int(lengths[r]), steps, width
                )
                row += 1
        # pad the wave to a power of two by repeating the last row:
        # same slot + same values, so the duplicate scatter writes are
        # idempotent and compilations stay bounded at log2(slots)+1
        pow2 = 1 << max(0, total - 1).bit_length()
        if pow2 > total:
            pad = pow2 - total
            padded = np.concatenate(
                [padded, np.repeat(padded[-1:], pad, axis=0)]
            )
            len_v, steps_v, eos_v, slots_v = (
                np.concatenate([v, np.repeat(v[-1:], pad)])
                for v in (len_v, steps_v, eos_v, slots_v)
            )
        self.state = _spec_join_many_jit(
            self.tparams, self.dparams, self.state,
            jnp.asarray(padded), jnp.asarray(len_v), jnp.asarray(steps_v),
            jnp.asarray(eos_v), jnp.asarray(slots_v),
            tcfg=self.tcfg, dcfg=self.dcfg,
        )
        occ = len(self._sessions)
        with self._lock:
            self._joins += total
            self._occupancy = occ
        tel = self._tel()
        if tel is not None:
            tel["batch_joins"].inc(total)
            tel["batch_occupancy"].set(occ)
        return handles

    def step(self) -> List[BatchHandle]:
        """Advance every resident session by one speculative round,
        retire finished slots, and return the handles whose LAST row
        just completed. No-op (empty list) on an empty batch."""
        self._check_owner()
        if not self._sessions:
            return []
        self.state, acc, prop = _spec_round_jit(
            self.tparams, self.dparams, self.state,
            tcfg=self.tcfg, dcfg=self.dcfg, gamma=self.cfg.gamma,
        )
        return self._retire(1, acc, prop)

    def step_block(self) -> List[BatchHandle]:
        """Advance by UP TO ``cfg.max_block`` rounds fused in one
        dispatch, then retire — the throughput path (the host-stepped
        per-round dispatch cost dominates round time at low occupancy;
        see _spec_round_block_jit). The block size is bounded so no
        row can reach its limit mid-block (a round commits at most
        gamma+1 tokens), which keeps retirement latency identical to
        single-round stepping; any resident eos-armed session CAN
        finish early, so its presence drops the block to one round."""
        self._check_owner()
        if not self._sessions:
            return []
        k = self.cfg.max_block
        if k > 1 and not any(
            s.handle.req.eos_id is not None for s in self._sessions.values()
        ):
            committed = np.asarray(self.state.committed)
            g1 = self.cfg.gamma + 1
            shortest = min(
                -(-(s.limit - int(committed[s.slot])) // g1)
                for s in self._sessions.values()
            )
            k = max(1, min(k, shortest))
        else:
            k = 1
        if k == 1:
            return self.step()
        import jax.numpy as jnp

        self.state, acc, prop = _spec_round_block_jit(
            self.tparams, self.dparams, self.state, jnp.int32(k),
            tcfg=self.tcfg, dcfg=self.dcfg, gamma=self.cfg.gamma,
        )
        return self._retire(k, acc, prop)

    def _retire(self, n_rounds: int, acc, prop) -> List[BatchHandle]:
        """Scan for finished rows after a round (or block), free their
        slots, and fold the round stats into the mirrors."""
        committed = np.asarray(self.state.committed)
        finished: List[BatchHandle] = []
        n_retired = 0
        for slot in list(self._sessions):
            sess = self._sessions[slot]
            if committed[slot] < sess.limit:
                continue
            # the slot's toks row is frozen once committed == limit
            # (capped commits land in the trash slot), so this read is
            # race-free even as later rounds keep stepping the batch
            row = np.asarray(self.state.toks[slot, : sess.width + sess.steps])
            sess.handle.out[sess.row_idx] = row
            sess.handle.rows_left -= 1
            if sess.handle.rows_left == 0:
                finished.append(sess.handle)
            del self._sessions[slot]
            self._free.append(slot)
            n_retired += 1
        occ = len(self._sessions)
        with self._lock:
            self._rounds += n_rounds
            self._retired += n_retired
            self._leaves += n_retired
            self._occupancy = occ
            self._accepted += int(acc)
            self._proposed += int(prop)
        tel = self._tel()
        if tel is not None:
            tel["batch_rounds"].inc(n_rounds)
            if n_retired:
                tel["batch_retired"].inc(n_retired)
                tel["batch_leaves"].inc(n_retired)
            tel["batch_occupancy"].set(occ)
        return finished

    # -- cross-thread introspection -------------------------------------

    def stats(self) -> dict:
        with self._lock:
            acc, prop = self._accepted, self._proposed
            return {
                "slots": self.cfg.slots,
                "occupancy": self._occupancy,
                "joins": self._joins,
                "leaves": self._leaves,
                "rounds": self._rounds,
                "retired": self._retired,
                "accepted_frac": acc / prop if prop else 0.0,
            }
