"""Read replicas: snapshot-consistent read copies refreshed off the push path.

The zero-copy data plane (PR 2) made training pushes donate the live
table in place — so a serving read against the live table must either
go through the store's executor (contending with training submits) or
risk jax's read-after-donate error. The read replica breaks the tie the
way the reference's replica protocol does (``parameter/replica.py``,
ref SetReplica/GetReplica): a PRIVATE copy of the table serves all
reads; training pushes keep donating the live table without ever
touching the replica's buffer.

Race-freedom is by construction, not by quiescing: the refresh rides
the store's own executor (``KVVector.snapshot`` — a submitted copy
step, or a plain ``pull`` for the hot-key subset), so it serializes
with in-flight donated pushes in timestamp order. Pull results never
alias the table and the snapshot step copies before returning, so the
replica's buffer is immune to every later donation — stronger than the
checkpoint path's drain-then-copy, which assumes the caller quiesced.

Consistency model: **snapshot** — every read between two ``refresh()``
calls sees one table version (``version`` counts refreshes, ``age_s()``
reports staleness). The refresh is the ONLY contention point with
training; schedule it off the request path (the frontend's background
refresher does).

``hot_keys`` mode: instead of snapshotting the whole ``[P, k]`` table,
the replica pulls just the hot rows into a compact ``[H, k]`` copy —
the serving working set of a power-law key distribution is orders of
magnitude smaller than the training table, so refresh stays O(hot)
instead of O(table). Keys outside the hot set report a miss and the
frontend falls through to the coalesced live-pull path.

``device=True`` mode: the snapshot STAYS on device as a (sharded) jax
array — ``KVVector.snapshot`` already returns a donation-immune device
copy, so holding it instead of ``np.asarray``-ing it to host is free,
and replica capacity scales with HBM instead of host RAM (hot-key mode
keeps a compact device ``[H, k]`` block). Reads become ONE jitted
device gather (row indices resolved host-side by the directory, padded
to a power of two so gather widths reuse a handful of compilations)
with a batched host shim for the numpy-facing ``pull`` contract.
``host_budget_bytes`` bounds what a HOST-mode replica may pin: a
refresh whose snapshot exceeds it fails loudly (keeping the last good
snapshot) instead of silently eating the serving host's RAM — the
device mode ignores the bound, which is exactly the point.
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Optional

import jax
import numpy as np

from ..system import faults


@functools.partial(jax.jit)
def _gather_rows(table, slots):
    """Device gather for the device-resident replica: ``table[slots]``
    compiled once per (table shape, padded slot width)."""
    return table[slots]


class ReadReplica:
    """Snapshot read copy of one store channel, served from host memory.

    ``store`` follows the KVVector protocol (``pull``/``wait_pull``/
    ``request``; ``snapshot(ch)`` when available, else
    ``table(ch, copy=True)`` after an executor drain). Reads
    (:meth:`pull`) snapshot the (table, directory) pair under a small
    lock and gather with numpy outside it — a concurrent refresh swaps
    the pair atomically but never mutates a published array.
    """

    def __init__(
        self,
        store,
        channel: int = 0,
        hot_keys: Optional[np.ndarray] = None,
        device: bool = False,
        host_budget_bytes: Optional[int] = None,
    ):
        self.store = store
        self.channel = int(channel)
        self.hot_keys = (
            None
            if hot_keys is None
            else np.unique(np.asarray(hot_keys, dtype=np.int64))
        )
        self.device = bool(device)
        self.host_budget_bytes = (
            None if host_budget_bytes is None else int(host_budget_bytes)
        )
        self._lock = threading.Lock()
        # host numpy snapshot, or a device jax array when device=True
        self._table = None  # guarded-by: _lock
        self.version = 0  # guarded-by: _lock
        self._refreshed_at = 0.0  # guarded-by: _lock
        from ..telemetry.instruments import cached_serve_instruments

        self._tel = cached_serve_instruments
        self.refresh()

    def _directory(self):
        """The channel's KeyDirectory (KVVector keeps one per channel,
        KVMap one per store)."""
        if hasattr(self.store, "channel"):
            return self.store.channel(self.channel).directory
        return self.store.directory

    # -- refresh (the ONLY path that touches the live store) --

    def refresh(self) -> int:
        """Take a fresh snapshot; returns the new version.

        Hot-key replicas refresh via a plain ``pull`` (results never
        alias the live table); full replicas via the store's submitted
        ``snapshot`` copy step — both serialize through the executor
        with training pushes, so there is no drain-and-hope window."""
        # fault point (doc/ROBUSTNESS.md): a dead shard's replica
        # refresh FAILS — it must not snapshot a corrupt table. The
        # frontend's background refresher logs-and-retries, keeping the
        # last good snapshot (whose age the degraded staleness bound
        # then judges).
        faults.inject("serve.refresh", detail=getattr(self.store, "name", ""))
        t0 = time.perf_counter()
        if self.hot_keys is not None:
            ts = self.store.pull(
                self.store.request(channel=self.channel), keys=self.hot_keys
            )
            fresh = self.store.wait_pull(ts)  # never aliases the table
        elif hasattr(self.store, "snapshot"):
            # the submitted copy step: already donation-immune, so the
            # device mode keeps the returned (sharded) array as-is
            fresh = self.store.executor.wait(self.store.snapshot(self.channel))
        else:  # stores without a snapshot step: checkpoint-path contract
            self.store.executor.wait_all(pop=False)
            fresh = self.store.table(self.channel, copy=True)
        if not self.device:
            fresh = np.asarray(fresh)
            if (
                self.host_budget_bytes is not None
                and fresh.nbytes > self.host_budget_bytes
            ):
                # fail BEFORE publishing: the last good snapshot keeps
                # serving (its age judged by the degraded staleness
                # bound) instead of this refresh silently pinning more
                # host RAM than the serving host was budgeted
                raise MemoryError(
                    f"host replica snapshot {fresh.nbytes} B exceeds "
                    f"host_budget_bytes={self.host_budget_bytes} — use "
                    "device=True to hold it in HBM instead"
                )
        with self._lock:
            self._table = fresh
            self.version += 1
            self._refreshed_at = time.monotonic()
            version = self.version
        tel = self._tel()
        if tel is not None:
            tel["replica_refresh"].observe(time.perf_counter() - t0)
        return version

    def age_s(self) -> float:
        with self._lock:
            return time.monotonic() - self._refreshed_at

    def nbytes(self) -> int:
        with self._lock:
            return 0 if self._table is None else self._table.nbytes

    # -- the read path (no store executor, no live-table reads) --

    def _rows(self, table, idx: np.ndarray) -> np.ndarray:
        """Gather snapshot rows by position: numpy fancy-indexing for a
        host snapshot, one jitted device gather + batched host shim for
        a device snapshot. Device indices are padded to the next power
        of two so arbitrary request sizes reuse a handful of gather
        compilations instead of one per width."""
        if not self.device:
            return table[idx]
        import jax.numpy as jnp

        m = int(idx.shape[0])
        mp = max(8, 1 << max(0, m - 1).bit_length())
        padded = np.zeros(mp, np.int32)
        padded[:m] = idx
        return np.asarray(_gather_rows(table, jnp.asarray(padded)))[:m]

    def pull(self, keys: np.ndarray) -> "tuple[np.ndarray, np.ndarray]":
        """Rows for ``keys`` from the snapshot: ``(values [n, k],
        hit_mask [n])``. Full-table replicas always hit (keys the
        directory doesn't know read 0, the device range-mask contract);
        hot-key replicas report misses PER KEY so the caller can fall
        through to a live pull for exactly the missed rows."""
        keys = np.asarray(keys, dtype=np.int64).ravel()
        with self._lock:
            table = self._table
        tel = self._tel()
        if self.hot_keys is None:
            slots = self._directory().slots(keys)
            miss = slots >= table.shape[0]
            vals = self._rows(table, np.minimum(slots, table.shape[0] - 1))
            if miss.any():
                vals = np.where(miss[:, None], 0, vals)
            if tel is not None:
                tel["replica_hits"].inc(len(keys))
            return vals, np.ones(len(keys), dtype=bool)
        pos = np.searchsorted(self.hot_keys, keys)
        posc = np.minimum(pos, len(self.hot_keys) - 1)
        hit = (pos < len(self.hot_keys)) & (self.hot_keys[posc] == keys)
        vals = np.zeros((len(keys), table.shape[1]), table.dtype)
        if hit.any():
            vals[hit] = self._rows(table, posc[hit])
        if tel is not None:
            n_hit = int(hit.sum())
            tel["replica_hits"].inc(n_hit)
            tel["replica_misses"].inc(len(keys) - n_hit)
        return vals, hit
