"""Request coalescing: merge in-flight pulls into one executor submit.

Serving traffic is read-heavy and hot-keyed: concurrent sessions ask
for overlapping key ranges within microseconds of each other. Issuing
each request as its own ``store.pull`` pays one executor submit + one
device gather + one host materialize PER REQUEST; the coalescer instead
accumulates requests for a bounded window (or until a key/request
budget fills), dedups the union key set host-side (``np.unique``), and
issues ONE submit for the whole batch. Each waiter then slices its rows
out of the union result by ``searchsorted`` — exact, because the union
contains every requested key by construction.

Two existing mechanisms make the merged pull cheap:

- the union of a hot working set repeats across windows, so the store's
  ``KeyDirectory`` slot-signature cache answers the hash/searchsorted
  pass AND the host→device index upload from cache (PR 2);
- one [U, k] gather materializes fewer total rows than N overlapping
  gathers — the overlap is fetched once.

Under load the coalescer gets MORE effective, not less: while the
flusher is executing window t, new arrivals accumulate into window t+1,
so the merge factor grows exactly when the executor needs relief. The
bench's acceptance number (``submits_per_request < 1`` at overlapping-
key load) is the stats pair this class counts.

Threading: clients call :meth:`pull` from any thread; ONE flusher
thread owns store submission order (the stateful stage of the PR-3
stateless-or-feeder rule). ``close()`` drains and joins.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional

import numpy as np

from ..telemetry import spans as telemetry_spans
from ..utils.retry import DeadlineExceeded


class _Window:
    """One coalesce generation: requests accumulated, then flushed as
    one pull. Published fields (``union``/``values``/``error``) are
    written by the flusher BEFORE ``done.set()`` and read by waiters
    only after ``done.wait()`` — the event is the fence, no lock."""

    __slots__ = (
        "keys", "n_requests", "deadline", "done", "union", "values",
        "error", "flows",
    )

    def __init__(self, deadline: float):
        self.keys: List[np.ndarray] = []
        self.n_requests = 0
        self.deadline = deadline
        self.done = threading.Event()
        self.union: Optional[np.ndarray] = None
        self.values: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None
        # timeline fan-in: the flow ids of the requests this window
        # merged (guarded like ``keys`` — appended under the owning
        # coalescer's _cv, read by the flusher after the hand-off)
        self.flows: List[int] = []


class PullTicket:
    """A client's claim on one coalesced pull. ``result()`` blocks for
    the window's flush, then slices this request's rows from the union
    result (each waiter pays its own searchsorted — the fan-out work
    parallelizes across client threads instead of serializing on the
    flusher)."""

    __slots__ = ("_win", "_keys")

    def __init__(self, win: _Window, keys: np.ndarray):
        self._win = win
        self._keys = keys

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self._win.done.wait(timeout):
            # explicit deadline semantics (utils/retry.py) — still a
            # TimeoutError; the frontend's degraded path catches this
            # as "live store past deadline"
            raise DeadlineExceeded(
                f"coalesced pull did not complete within {timeout}s",
                op="serve:coalesced-pull", deadline_s=timeout,
            )
        if self._win.error is not None:
            raise RuntimeError(
                "coalesced pull failed"
            ) from self._win.error
        pos = np.searchsorted(self._win.union, self._keys)
        return self._win.values[pos]


# owner-thread: flusher
class PullCoalescer:
    """Merge concurrent pulls against one store channel.

    ``store`` is any parameter store exposing the ``pull(task, keys)``
    / ``wait_pull(ts)`` / ``request(channel=...)`` protocol (KVVector,
    KVMap). ``window_s`` bounds the latency cost of waiting for merge
    partners — the p50 tax that buys the p99 win; ``max_keys`` /
    ``max_requests`` flush a window early so one elephant request
    cannot hold the door open for the whole window.
    """

    def __init__(
        self,
        store,
        channel: int = 0,
        window_s: float = 0.002,
        max_keys: int = 1 << 16,
        max_requests: int = 256,
    ):
        self.store = store
        self.channel = int(channel)
        self.window_s = float(window_s)
        self.max_keys = int(max_keys)
        self.max_requests = int(max_requests)
        self._cv = threading.Condition()
        self._open: Optional[_Window] = None  # guarded-by: _cv
        self._open_keys = 0  # guarded-by: _cv — total keys staged in _open
        self._closed = False  # guarded-by: _cv
        # stats (monotonic; the serve bench reads them): requests in,
        # submits out, keys requested vs keys actually pulled
        self.requests_total = 0  # guarded-by: _cv
        self.submits_total = 0  # guarded-by: _cv
        self.requested_keys_total = 0  # guarded-by: _cv
        self.union_keys_total = 0  # guarded-by: _cv
        from ..telemetry.instruments import cached_serve_instruments

        self._tel = cached_serve_instruments
        self._thread = threading.Thread(
            target=self._flush_loop, name="serve-coalescer", daemon=True
        )
        self._thread.start()

    # -- client side --

    def pull(self, keys: np.ndarray) -> PullTicket:
        """Stage one request into the current window; returns a ticket.
        Raises RuntimeError after :meth:`close`."""
        keys = np.asarray(keys, dtype=np.int64).ravel()
        with self._cv:
            if self._closed:
                raise RuntimeError("PullCoalescer is closed")
            win = self._open
            fresh = win is None
            if fresh:
                win = _Window(time.monotonic() + self.window_s)
                self._open = win
                self._open_keys = 0
            win.keys.append(keys)
            win.n_requests += 1
            fid = telemetry_spans.current_flow()
            if fid is not None:
                win.flows.append(fid)
            self._open_keys += len(keys)
            self.requests_total += 1
            self.requested_keys_total += len(keys)
            full = (
                self._open_keys >= self.max_keys
                or win.n_requests >= self.max_requests
            )
            if full:
                win.deadline = 0.0  # flush now
            if fresh or full:
                # only these change anything the flusher can act on (a
                # new deadline to sleep toward, or an early flush); a
                # mid-window arrival would just wake it into re-checking
                # the same deadline — at thousands of submits/sec those
                # wakeups are pure context-switch tax on the hot path
                self._cv.notify_all()
        # deliberately NOT counted in ps_serve_requests_total: that
        # counter means "admitted through the serving door" and the
        # frontend counts it there — a second increment here would
        # double-count every coalesced pull (and inflate it by replica
        # misses); this class's own volume lives in the
        # ps_serve_coalesce_* counters
        return PullTicket(win, keys)

    # -- flusher thread --

    def _take_window_locked(self) -> Optional[_Window]:  # holds-lock: _cv
        """The open window once its deadline passed (or it filled), else
        None after bounding the wait to the deadline."""
        win = self._open
        if win is None:
            self._cv.wait()
            return None
        now = time.monotonic()
        if now < win.deadline:
            self._cv.wait(win.deadline - now)
            return None
        self._open = None
        self._open_keys = 0
        return win

    def _flush_loop(self) -> None:
        while True:
            with self._cv:
                if self._closed and self._open is None:
                    return
                if self._closed and self._open is not None:
                    win, self._open = self._open, None
                else:
                    win = self._take_window_locked()
                    if win is None:
                        continue
            self._flush(win)

    def _flush(self, win: _Window) -> None:
        # the flush gets its own flow id; the span's ``flows`` list
        # names the merged requests, so the timeline draws fan-in
        # arrows request → flush, and the executor step submitted
        # below correlates to the flush (executor.submit captures the
        # active flow)
        fid = telemetry_spans.maybe_new_flow()

        def pull_union():
            union = np.unique(np.concatenate(win.keys))
            ts = self.store.pull(
                self.store.request(channel=self.channel), keys=union
            )
            return union, np.asarray(self.store.wait_pull(ts))

        try:
            if fid is not None:
                with telemetry_spans.flow_scope(fid):
                    with telemetry_spans.span(
                        "serve.coalesce.flush",
                        merged=win.n_requests,
                        flows=list(win.flows),
                    ):
                        union, values = pull_union()
            else:  # tracing off: no span machinery on the flush path
                union, values = pull_union()
            win.union = union
            win.values = values
            with self._cv:
                self.submits_total += 1
                self.union_keys_total += len(union)
            tel = self._tel()
            if tel is not None:
                tel["coalesce_submits"].inc()
                tel["coalesce_merged_requests"].inc(win.n_requests)
                tel["coalesce_union_keys"].inc(len(union))
        except BaseException as e:  # publish; every waiter re-raises
            win.error = e
        finally:
            win.done.set()

    def close(self) -> None:
        """Flush whatever is staged, stop and join the flusher."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._thread.join(timeout=60)

    # -- introspection (the serve bench's coalescing-win numbers) --

    def stats(self) -> dict:
        with self._cv:
            req = self.requests_total
            sub = self.submits_total
            return {
                "requests": req,
                "submits": sub,
                "submits_per_request": round(sub / req, 4) if req else None,
                "requested_keys": self.requested_keys_total,
                "union_keys": self.union_keys_total,
                "key_dedup_factor": round(
                    self.requested_keys_total
                    / max(1, self.union_keys_total), 3
                ),
            }
