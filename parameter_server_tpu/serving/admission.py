"""Admission control: token bucket + queue-depth shedding.

The serving plane's overload story (doc/SERVING.md): an open-loop
client population does not slow down when the server does, so without
admission control the request queue — and therefore p99 — grows without
bound the moment offered load crosses capacity. The controller bounds
both: a token bucket caps the sustained accept rate (with a burst
allowance for arrival jitter), and a queue-depth gate sheds when the
backlog already exceeds what the latency SLO could absorb. Rejections
are EXPLICIT (:class:`RejectedError`, the HTTP-429 analog, carrying a
``retry_after_s`` hint) — a shed request costs microseconds; an
admitted request that can't meet its deadline costs a client timeout.

The reference server throttles through its bounded-delay message
clocks (executor.cc); serving inverts the direction: the clock bounds
how far the TRAINER may run ahead, the bucket bounds how fast CLIENTS
may push in.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional


class RejectedError(Exception):
    """Explicit 429-style rejection. ``reason`` is ``"rate"`` (token
    bucket empty) or ``"queue"`` (backlog past ``max_queue_depth``);
    ``retry_after_s`` is the earliest time a retry could be admitted
    (rate) or a heuristic backoff (queue)."""

    def __init__(self, reason: str, retry_after_s: float):
        super().__init__(
            f"request shed ({reason}); retry after {retry_after_s:.3f}s"
        )
        self.reason = reason
        self.retry_after_s = retry_after_s


class TokenBucket:
    """Classic token bucket: ``rate`` tokens/s refill, ``burst`` cap.

    ``try_acquire`` never blocks — admission control sheds instead of
    queueing at the rate limiter (queueing is the failure mode this
    exists to bound). ``clock`` is injectable so tests are
    deterministic; production uses ``time.monotonic``.
    """

    def __init__(
        self,
        rate: float,
        burst: float,
        clock: Callable[[], float] = time.monotonic,
    ):
        if rate <= 0:
            raise ValueError(f"rate must be > 0, got {rate}")
        if burst < 1:
            raise ValueError(f"burst must be >= 1, got {burst}")
        self.rate = float(rate)
        self.burst = float(burst)
        self._clock = clock
        self._tokens = float(burst)  # guarded-by: _lock
        self._last = clock()  # guarded-by: _lock
        self._lock = threading.Lock()

    def _refill_locked(self, now: float) -> None:  # holds-lock: _lock
        self._tokens = min(
            self.burst, self._tokens + (now - self._last) * self.rate
        )
        self._last = now

    def try_acquire(self, n: float = 1.0) -> Optional[float]:
        """Take ``n`` tokens. Returns None on success, else the seconds
        until ``n`` tokens will have refilled (the retry-after hint)."""
        with self._lock:
            # clock sampled INSIDE the lock: two concurrent callers
            # sampling outside could apply refills with out-of-order
            # timestamps, rewinding _last and re-crediting the same
            # interval (the read-stale-then-write-under-lock pattern
            # pslint's lock pass exists to catch)
            self._refill_locked(self._clock())
            if self._tokens >= n:
                self._tokens -= n
                return None
            return (n - self._tokens) / self.rate

    def available(self) -> float:
        with self._lock:
            self._refill_locked(self._clock())
            return self._tokens


class AdmissionController:
    """The serving door: rate gate, then backlog gate.

    ``depth_fn`` reports the current backlog the latency SLO must
    absorb (e.g. ``executor.pending_count`` for a bare store; the
    frontend does NOT use it — its depth bounds are per-lane and
    check-and-reserve atomically inside ``submit()``, which a read-only
    callback sampled outside the enqueue lock cannot do). Order
    matters: the rate gate runs FIRST so a sustained overload drains
    tokens and sheds cheaply before the backlog ever builds — the
    queue gate is the safety net for slow-request pileups below the
    rate cap (a decode burst behind a device stall).

    ``rate <= 0`` disables the bucket (queue gate only); ``max_queue_depth
    <= 0`` disables the queue gate. Thread-safe; counters live in the
    telemetry registry (``ps_serve_shed_total{reason=...}``).
    """

    def __init__(
        self,
        rate: float = 0.0,
        burst: float = 1.0,
        max_queue_depth: int = 0,
        depth_fn: Optional[Callable[[], int]] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.bucket = (
            TokenBucket(rate, max(1.0, burst), clock) if rate > 0 else None
        )
        self.max_queue_depth = int(max_queue_depth)
        self.depth_fn = depth_fn
        from ..telemetry.instruments import cached_serve_instruments

        self._tel = cached_serve_instruments

    def queue_retry_s(self, depth: int) -> float:
        """Retry-after hint for a queue shed: the backlog drains at
        ~the admitted rate, so tell the client to come back after its
        share of it (capped; 50ms when no rate gate is configured).
        Shared by the depth gate here and the frontend's per-lane
        check-and-reserve gates, so both lanes quote the same
        heuristic."""
        rate = self.bucket.rate if self.bucket is not None else 0.0
        return min(depth / rate, 5.0) if rate > 0 else 0.05

    def admit(self, cost: float = 1.0) -> None:
        """Admit one request (``cost`` tokens) or raise
        :class:`RejectedError`. Success returns None and consumes the
        tokens; the caller owns the request from here."""
        if self.bucket is not None:
            retry = self.bucket.try_acquire(cost)
            if retry is not None:
                tel = self._tel()
                if tel is not None:
                    tel["shed"].labels(reason="rate").inc()
                raise RejectedError("rate", retry)
        if self.max_queue_depth > 0 and self.depth_fn is not None:
            depth = self.depth_fn()
            if depth >= self.max_queue_depth:
                tel = self._tel()
                if tel is not None:
                    tel["shed"].labels(reason="queue").inc()
                raise RejectedError("queue", self.queue_retry_s(depth))
