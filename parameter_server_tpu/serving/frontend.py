"""ServeFrontend: the request-path composition.

One object owns the serving door for a table (and optionally an LM):

    client threads ──submit()──► admission gate ──► bounded work queue
                                     │ RejectedError (429)   │
                                     ▼                       ▼
                               shed counters        worker pool (N)
                                                     │        │
                                       replica gather│        │decode worker
                                     (hot hit, host) │        │(speculative)
                                                     ▼        ▼
                                            coalescer (misses/no-replica)
                                                     │ one executor submit
                                                     ▼ per window
                                               live table pull

Requests are typed (:class:`PullRequest` — raw rows;
:class:`PredictRequest` — sparse logistic margins over pulled weights;
:class:`DecodeRequest` — LM generation through a caller-supplied
``decode_fn``, normally ``models.speculative.speculative_generate``).
``submit`` is non-blocking: it either raises :class:`RejectedError`
at the door or returns a :class:`Ticket` whose ``result()`` waits for
a worker to complete the request. Latency is measured submit→complete
— the number the open-loop bench quotes as p50/p99.

Elasticity: :meth:`pause` gates the workers (admitted requests keep
queueing; the admission depth gate sheds past the bound — never an
error), :meth:`quiesce` waits out in-flight executions, and
:meth:`rebind` points the frontend at the post-resize store. Together
they make the ~52ms elastic stop-the-world invisible to clients except
as a latency bump (tests/test_serving.py pins this).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Callable, Optional

import numpy as np

from .admission import AdmissionController, RejectedError  # noqa: F401  (re-export: the door's exception belongs to the frontend API)
from .coalescer import PullCoalescer
from .replica import ReadReplica
from ..system import faults
from ..telemetry import spans as telemetry_spans
from ..utils.retry import DeadlineExceeded


class DegradedError(Exception):
    """503-style failure degradation — DISTINCT from the admission 429
    (:class:`~.admission.RejectedError`). A shed says "you sent too
    much, back off and retry"; degraded says "the live store is dead or
    past its deadline AND the stale-read fallback could not answer"
    (no replica, staleness past the bound, or keys outside its
    coverage). Separately observable on purpose: overload shedding and
    failure degradation need different operator responses
    (doc/ROBUSTNESS.md "Degraded vs shed").

    ``reason`` is ``"no-replica"`` | ``"stale"`` | ``"replica-miss"``.
    """

    def __init__(self, reason: str, detail: str = ""):
        super().__init__(
            f"live store unavailable and degraded path cannot serve "
            f"({reason})" + (f": {detail}" if detail else "")
        )
        self.reason = reason


@dataclasses.dataclass
class PullRequest:
    """Raw rows for ``keys`` (global int64 key ids)."""

    keys: np.ndarray
    channel: int = 0


@dataclasses.dataclass
class PredictRequest:
    """Sparse logistic scores: CSR rows over global keys; the response
    is ``sigmoid(sum_j w[key_ij])`` per row (the binary-feature CTR
    predict of the reference's linear apps)."""

    indices: np.ndarray  # [nnz] global keys
    indptr: np.ndarray  # [rows + 1]
    channel: int = 0


@dataclasses.dataclass
class DecodeRequest:
    """LM generation; executed by the frontend's ``decode_fn`` on the
    dedicated decode worker (heavy requests must not head-of-line-block
    the microsecond pull lane)."""

    prompt: np.ndarray  # [B, P] int32
    steps: int
    prompt_lengths: Optional[np.ndarray] = None
    eos_id: Optional[int] = None


@dataclasses.dataclass
class ServeConfig:
    # admission (0 disables a gate)
    admission_rate: float = 0.0  # requests/s sustained
    admission_burst: float = 32.0
    max_queue_depth: int = 1024
    # coalescing
    coalesce_window_s: float = 0.002
    coalesce_max_keys: int = 1 << 16
    coalesce_max_requests: int = 256
    # read replica: "off" (all pulls coalesce to the live table),
    # "full" (whole-table snapshot), "hot" with hot_keys set, or
    # "fallback" — a full snapshot that is NOT consulted on the happy
    # path (reads stay live/fresh through the coalescer) and serves
    # only as the degraded path when the live store fails or misses
    # its deadline (doc/ROBUSTNESS.md "Degraded-mode serving")
    replica: str = "full"
    hot_keys: Optional[np.ndarray] = None
    replica_refresh_s: Optional[float] = None  # None = manual refresh()
    # device-resident replica (serving/replica.py): keep the snapshot
    # as a (sharded) jax array and serve reads as jitted gathers —
    # replica capacity scales with HBM, not host RAM. The host budget
    # bounds what a HOST-mode replica may pin (a refresh past it fails
    # loudly); device mode ignores it by design
    replica_device: bool = False
    replica_host_budget_bytes: Optional[int] = None
    # worker pool (pull/predict lane) — decode gets its own worker
    workers: int = 2
    # degraded-mode serving: a live (coalesced) pull that raises — or
    # exceeds live_pull_deadline_s (0 = no deadline) — falls back to
    # the read replica IF its snapshot is younger than
    # degraded_max_staleness_s; otherwise the request fails with the
    # 503-style DegradedError (vs the admission 429). The staleness
    # bound is deliberately FINITE by default: an unbounded default
    # would let a forgotten config serve arbitrarily old parameters
    # forever with only a counter to notice — a store outage must
    # become loud within a bounded window, not silently stale
    live_pull_deadline_s: float = 0.0
    degraded_max_staleness_s: float = 60.0


class Ticket:
    """One admitted request's completion handle. ``flow`` is the
    request's timeline flow id (telemetry/timeline.py) when a span sink
    is installed — submit, execution, coalesced pull, executor step and
    reply all correlate through it."""

    __slots__ = (
        "_done", "value", "error", "t_submit", "t_done", "kind", "flow",
    )

    def __init__(self, kind: str, flow: Optional[int] = None):
        self._done = threading.Event()
        self.value = None
        self.error: Optional[BaseException] = None
        self.t_submit = time.perf_counter()
        self.t_done = 0.0
        self.kind = kind
        self.flow = flow

    def _complete(self, value=None, error=None) -> None:
        self.value = value
        self.error = error
        self.t_done = time.perf_counter()
        self._done.set()

    def latency_s(self) -> float:
        return self.t_done - self.t_submit

    def result(self, timeout: Optional[float] = None):
        if not self._done.wait(timeout):
            # explicit deadline semantics (utils/retry.py): still a
            # TimeoutError for legacy callers, but diagnosable
            raise DeadlineExceeded(
                f"{self.kind} request did not complete within "
                f"{timeout}s (submitted "
                f"{time.perf_counter() - self.t_submit:.3f}s ago)",
                op=f"serve:{self.kind}", deadline_s=timeout,
            )
        if self.error is not None:
            raise self.error
        return self.value


class ServeFrontend:
    """Concurrent serving sessions against one store channel (+ LM).

    ``store`` follows the KVVector protocol; ``decode_fn(req) -> array``
    (if given) enables :class:`DecodeRequest`. ``start()`` spins the
    worker pool; ``close()`` drains and joins every thread the frontend
    started.
    """

    def __init__(
        self,
        store,
        config: Optional[ServeConfig] = None,
        channel: int = 0,
        decode_fn: Optional[Callable[[DecodeRequest], np.ndarray]] = None,
        batcher=None,
    ):
        self.cfg = config or ServeConfig()
        self.store = store
        self.channel = int(channel)
        if decode_fn is not None and batcher is not None:
            raise ValueError(
                "pass decode_fn (one sequential call per request) OR "
                "batcher (continuous batching), not both"
            )
        self.decode_fn = decode_fn
        # serving/batcher.py ContinuousBatcher: the decode worker
        # becomes its single-owner scheduler thread (_batch_loop)
        self.batcher = batcher
        self._cv = threading.Condition()
        self._queue: deque = deque()  # guarded-by: _cv — pull/predict lane
        self._decode_queue: deque = deque()  # guarded-by: _cv
        # per-LANE in-flight counts (admitted, not completed): each
        # lane carries its own max_queue_depth bound (submit()) — a
        # decode backlog shedding microsecond pulls, or pull overload
        # starving decodes, would reintroduce exactly the head-of-line
        # coupling the dedicated decode worker removes
        self._in_flight = 0  # guarded-by: _cv — pull/predict lane
        self._in_flight_decode = 0  # guarded-by: _cv — decode lane
        self._executing = 0  # guarded-by: _cv — popped, running right now
        self._paused = False  # guarded-by: _cv — elastic stop-the-world
        self._closed = False  # guarded-by: _cv
        self._threads: list = []
        self._refresher: Optional[threading.Thread] = None
        self._stop_refresh = threading.Event()
        self.completed = 0  # guarded-by: _cv
        # rate gate only: the depth bounds are PER-LANE and owned by
        # submit() (check+reserve in one critical section), not by the
        # controller's shared depth_fn hook — one shared count would
        # couple the lanes, and a depth_fn read outside the enqueue
        # lock would let concurrent submits overshoot the bound
        self.admission = AdmissionController(
            rate=self.cfg.admission_rate,
            burst=self.cfg.admission_burst,
        )
        # replica config is validated (and its first refresh runs)
        # BEFORE the coalescer exists: PullCoalescer starts its flusher
        # thread in its constructor, so raising after building it would
        # leak a live thread with no close() to ever reach it
        self.replica: Optional[ReadReplica] = None
        if self.cfg.replica == "hot":
            if self.cfg.hot_keys is None:
                raise ValueError("replica='hot' needs ServeConfig.hot_keys")
            self.replica = ReadReplica(
                store, channel, hot_keys=self.cfg.hot_keys,
                device=self.cfg.replica_device,
                host_budget_bytes=self.cfg.replica_host_budget_bytes,
            )
        elif self.cfg.replica in ("full", "fallback"):
            self.replica = ReadReplica(
                store, channel,
                device=self.cfg.replica_device,
                host_budget_bytes=self.cfg.replica_host_budget_bytes,
            )
        elif self.cfg.replica != "off":
            raise ValueError(
                f"ServeConfig.replica must be 'off'|'full'|'hot'|"
                f"'fallback', got {self.cfg.replica!r}"
            )
        self.degraded_served = 0  # guarded-by: _cv — stale-replica answers
        self.coalescer = PullCoalescer(
            store,
            channel=channel,
            window_s=self.cfg.coalesce_window_s,
            max_keys=self.cfg.coalesce_max_keys,
            max_requests=self.cfg.coalesce_max_requests,
        )
        from ..telemetry.instruments import cached_serve_instruments

        self._tel = cached_serve_instruments

    # -- lifecycle --

    def start(self) -> "ServeFrontend":
        if self._threads:
            return self
        for i in range(max(1, self.cfg.workers)):
            t = threading.Thread(
                # pslint: disable=guarded-access — passing the deque REFERENCE to the worker before start(); Thread.start() is the happens-before edge, and the reference itself is never reassigned
                target=self._worker_loop, args=(self._queue,),
                name=f"serve-worker-{i}", daemon=True,
            )
            t.start()
            self._threads.append(t)
        if self.batcher is not None:
            # the continuous batcher's single-owner scheduler: same
            # thread name and lane, different loop — it multiplexes the
            # whole decode queue into one running speculative call
            t = threading.Thread(
                target=self._batch_loop, name="serve-decode", daemon=True,
            )
            t.start()
            self._threads.append(t)
        elif self.decode_fn is not None:
            t = threading.Thread(
                # pslint: disable=guarded-access — same reference-pass-before-start() as the worker spawn above
                target=self._worker_loop, args=(self._decode_queue,),
                name="serve-decode", daemon=True,
            )
            t.start()
            self._threads.append(t)
        if self.cfg.replica_refresh_s and self.replica is not None:
            self._refresher = threading.Thread(
                target=self._refresh_loop, name="serve-replica-refresh",
                daemon=True,
            )
            self._refresher.start()
        return self

    def close(self) -> None:
        """Drain queued work (closing un-pauses), then join every
        thread the frontend started."""
        with self._cv:
            self._closed = True
            self._paused = False  # workers must drain, not strand
            self._cv.notify_all()
        self._stop_refresh.set()
        for t in self._threads:
            t.join(timeout=60)
        self._threads = []
        if self._refresher is not None:
            self._refresher.join(timeout=60)
            self._refresher = None
        self.coalescer.close()

    # -- elasticity (system/elastic.py integration) --

    def pause(self) -> None:
        """Gate the workers: admitted requests queue (and shed past the
        admission depth bound) instead of touching a store whose mesh
        is being rebuilt. In-flight executions finish against the old
        store — :meth:`quiesce` waits them out."""
        with self._cv:
            self._paused = True

    def quiesce(self, timeout: float = 30.0) -> None:
        """Block until no worker is mid-execution (call after
        :meth:`pause`, before tearing down the old store)."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._executing > 0:
                left = deadline - time.monotonic()
                if left <= 0:
                    raise TimeoutError("serve workers did not quiesce")
                self._cv.wait(left)

    def resume(self) -> None:
        with self._cv:
            self._paused = False
            self._cv.notify_all()

    def rebind(self, store, refresh_replica: bool = True) -> None:
        """Point the frontend at the post-resize store (the elastic
        coordinator rebuilds the worker and its tables; key→slot
        hashing is stable across resizes, so requests queued across the
        pause stay valid). Call between :meth:`pause`/:meth:`quiesce`
        and :meth:`resume`."""
        old = self.coalescer
        self.store = store
        self.coalescer = PullCoalescer(
            store,
            channel=self.channel,
            window_s=self.cfg.coalesce_window_s,
            max_keys=self.cfg.coalesce_max_keys,
            max_requests=self.cfg.coalesce_max_requests,
        )
        old.close()
        if self.replica is not None:
            self.replica.store = store
            if refresh_replica:
                self.replica.refresh()

    # -- the door --

    def depth(self) -> int:
        """The PULL/PREDICT lane's backlog: admitted, uncompleted
        requests (queued + executing). The decode lane is bounded
        separately (same-sized, in :meth:`submit`) — one shared count
        would let a slow-decode pileup shed the microsecond pull
        traffic at the door."""
        with self._cv:
            return self._in_flight

    def _queue_retry_s(self, depth: int) -> float:
        # the admission controller's drain-rate heuristic, applied to
        # this lane's depth (serving/admission.py queue_retry_s)
        return self.admission.queue_retry_s(depth)

    def submit(self, req) -> Ticket:
        """Admit and enqueue one request; raises
        :class:`~.admission.RejectedError` (the 429) at the door."""
        if (
            isinstance(req, DecodeRequest)
            and self.decode_fn is None
            and self.batcher is None
        ):
            raise ValueError(
                "this frontend has no decode lane (decode_fn or batcher)"
            )
        if getattr(req, "channel", self.channel) != self.channel:
            # one frontend serves ONE channel (its replica and
            # coalescer are bound to it); silently answering another
            # channel's request with this channel's rows would be a
            # wrong-data bug, so reject loudly — stand up a frontend
            # per served channel instead
            raise ValueError(
                f"this frontend serves channel {self.channel}, got a "
                f"request for channel {req.channel}"
            )
        decode = isinstance(req, DecodeRequest)
        with self._cv:
            # closed-check BEFORE the admission gate: a submit racing
            # close() must not burn tokens (or count as admitted) for a
            # request that can never enqueue
            if self._closed:
                raise RuntimeError("ServeFrontend is closed")
            # per-LANE depth gate, check AND reserve in this ONE
            # critical section: each lane takes the same-sized bound
            # against its own backlog (a shared count would let a
            # decode pileup shed microsecond pulls — and vice versa),
            # and checking in one section then reserving in another
            # would let concurrent submits overshoot the bound by the
            # submitter count. The reservation is released below on any
            # rejection between here and enqueue.
            lane = self._in_flight_decode if decode else self._in_flight
            if 0 < self.cfg.max_queue_depth <= lane:
                tel = self._tel()
                if tel is not None:
                    tel["shed"].labels(reason="queue").inc()
                raise RejectedError("queue", self._queue_retry_s(lane))
            if decode:
                self._in_flight_decode += 1
            else:
                self._in_flight += 1
        try:
            self.admission.admit()  # rate gate (depth owned above)
        except BaseException:
            with self._cv:
                if decode:
                    self._in_flight_decode -= 1
                else:
                    self._in_flight -= 1
            raise
        kind = (
            "pull" if isinstance(req, PullRequest)
            else "predict" if isinstance(req, PredictRequest)
            else "decode"
        )
        fid = telemetry_spans.maybe_new_flow()
        ticket = Ticket(kind, flow=fid)
        if fid is not None:
            # zero-duration submit marker: the gap to the execute span
            # is the request's queue-wait in the timeline
            telemetry_spans.emit(
                {
                    "kind": "span",
                    "name": "serve.submit",
                    "t_wall": time.time(),
                    "dur_s": 0.0,
                    "flow": fid,
                    "req": kind,
                }
            )
        tel = self._tel()
        with self._cv:
            if self._closed:  # closed during admit: nothing enqueued
                if decode:
                    self._in_flight_decode -= 1
                else:
                    self._in_flight -= 1
                raise RuntimeError("ServeFrontend is closed")
            if decode:
                self._decode_queue.append((req, ticket))
            else:
                self._queue.append((req, ticket))
            depth = self._in_flight + self._in_flight_decode
            self._cv.notify_all()
        # counted only once the request is really ENQUEUED, so
        # requests_total reconciles with tickets issued
        if tel is not None:
            tel["requests"].labels(kind=kind).inc()
            tel["queue_depth"].set(depth)
        return ticket

    # -- workers --

    def _worker_loop(self, queue: deque) -> None:
        # pslint: disable=guarded-access — identity check against a reference that is assigned once in __init__ and never rebound; no element access happens here
        decode_lane = queue is self._decode_queue
        while True:
            with self._cv:
                while (not queue or self._paused) and not self._closed:
                    self._cv.wait()
                if not queue:  # closed and drained
                    return
                req, ticket = queue.popleft()
                self._executing += 1
            try:
                # span only when the request carries a flow (sink was
                # installed at submit) — the µs pull lane pays nothing
                # for tracing that is off
                if ticket.flow is not None:
                    span_name = (
                        "serve.decode" if ticket.kind == "decode"
                        else "serve.execute"
                    )
                    with telemetry_spans.flow_scope(ticket.flow):
                        with telemetry_spans.span(span_name, req=ticket.kind):
                            value = self._execute(req)
                else:
                    value = self._execute(req)
                err = None
            except BaseException as e:
                value, err = None, e
            ticket._complete(value, err)
            if ticket.flow is not None:
                # reply marker: completion handed back to the waiter —
                # closes the request's flow in the timeline
                telemetry_spans.emit(
                    {
                        "kind": "span",
                        "name": "serve.reply",
                        "t_wall": time.time(),
                        "dur_s": 0.0,
                        "flow": ticket.flow,
                        "latency_s": ticket.latency_s(),
                        "req": ticket.kind,
                        **({"error": type(err).__name__} if err else {}),
                    }
                )
            with self._cv:
                self._executing -= 1
                if decode_lane:
                    self._in_flight_decode -= 1
                else:
                    self._in_flight -= 1
                self.completed += 1
                self._cv.notify_all()
            tel = self._tel()
            if tel is not None:
                tel["latency"].labels(kind=ticket.kind).observe(
                    ticket.latency_s()
                )

    def _finish_decode_ticket(self, ticket: Ticket, value, err) -> None:
        """Completion bookkeeping for one batched decode request —
        the tail of _worker_loop, factored out for _batch_loop (which
        completes tickets at round boundaries, not per pop)."""
        ticket._complete(value, err)
        if ticket.flow is not None:
            telemetry_spans.emit(
                {
                    "kind": "span",
                    "name": "serve.reply",
                    "t_wall": time.time(),
                    "dur_s": 0.0,
                    "flow": ticket.flow,
                    "latency_s": ticket.latency_s(),
                    "req": ticket.kind,
                    **({"error": type(err).__name__} if err else {}),
                }
            )
        with self._cv:
            self._in_flight_decode -= 1
            self.completed += 1
            self._cv.notify_all()
        tel = self._tel()
        if tel is not None:
            tel["latency"].labels(kind=ticket.kind).observe(
                ticket.latency_s()
            )

    def _batch_loop(self) -> None:
        """The continuous batcher's single-owner scheduler (PR 3
        stateless-or-feeder rule): this thread alone calls
        ``batcher.admit_many``/``step_block``. Sessions join at round
        boundaries
        into free slots; finished sessions retire between rounds
        without stalling the rest; requests too wide for the current
        free set wait at the head of the queue (admission sheds past
        the lane depth bound long before that).

        Pause semantics differ from _worker_loop on purpose: ``pause``
        gates NEW joins (the queue holds), but resident sessions keep
        stepping — decode rounds touch only device model state, never
        the store, so serving continues straight through an elastic
        resize or live rebalance (pinned by tests). Rounds therefore do
        not count into ``_executing``/:meth:`quiesce`."""
        b = self.batcher
        active = False
        while True:
            admits = []
            with self._cv:
                while (
                    (not self._decode_queue or self._paused)
                    and not self._closed
                    and not active
                ):
                    self._cv.wait()
                if self._closed and not self._decode_queue and not active:
                    return
                if not self._paused or self._closed:  # closing drains
                    free = b.free_slots()
                    while self._decode_queue:
                        req, _t = self._decode_queue[0]
                        try:
                            rows = int(np.asarray(req.prompt).shape[0])
                        except Exception:
                            rows = 1  # malformed: admit() rejects it below
                        if rows > free:
                            break
                        admits.append(self._decode_queue.popleft())
                        free -= rows
            if admits:
                try:
                    # the whole wave joins in ONE fused call (the
                    # per-call join cost dominates admission otherwise)
                    b.admit_many(admits)
                except ValueError:
                    # a malformed request poisons the wave-validate;
                    # re-admit one by one so only the bad ones fail
                    for req, ticket in admits:
                        try:
                            b.admit(req, context=ticket)
                        except BaseException as e:
                            self._finish_decode_ticket(ticket, None, e)
                except BaseException as e:
                    for _req, ticket in admits:
                        self._finish_decode_ticket(ticket, None, e)
            for handle in b.step_block():
                out = handle.out
                tel = self._tel()
                if tel is not None:
                    tel["decode_tokens"].inc(
                        out.shape[0] * int(handle.req.steps)
                    )
                self._finish_decode_ticket(handle.context, out, None)
            active = b.active_sessions() > 0

    def _live_pull(self, keys: np.ndarray) -> np.ndarray:
        """One coalesced pull against the live store, bounded by
        ``live_pull_deadline_s``. The ``serve.pull`` fault point
        (doc/ROBUSTNESS.md) sits here — the exact place a dead shard
        manifests to serving — so drills can kill the store path
        without touching the admission door or the replica."""
        # inject() covers both documented kinds: "raise" raises after
        # any delay_s, "stall" sleeps delay_s and falls through
        faults.inject("serve.pull", detail=getattr(self.store, "name", ""))
        deadline = self.cfg.live_pull_deadline_s or None
        return self.coalescer.pull(keys).result(deadline)

    def _degraded_fallback(
        self, keys: np.ndarray, cause: BaseException
    ) -> np.ndarray:
        """The live store failed (or deadlined): serve from the read
        replica when its snapshot is inside the staleness bound and
        covers every key; otherwise raise the 503-style DegradedError.
        Never catches RejectedError — overload sheds are the door's
        verdict, not a store failure to degrade around."""
        tel = self._tel()
        r = self.replica
        reason = None
        if r is None:
            reason, detail = "no-replica", f"live pull failed: {cause}"
        else:
            age = r.age_s()
            if age > self.cfg.degraded_max_staleness_s:
                reason, detail = "stale", (
                    f"replica {age:.1f}s old > "
                    f"{self.cfg.degraded_max_staleness_s}s bound"
                )
        if reason is None:
            vals, hit = r.pull(keys)
            if hit.all():
                with self._cv:
                    self.degraded_served += 1
                if tel is not None:
                    tel["degraded"].labels(outcome="served").inc()
                return vals
            reason, detail = "replica-miss", (
                f"{int((~hit).sum())}/{len(hit)} keys outside the "
                "replica's coverage"
            )
        if tel is not None:
            tel["degraded"].labels(outcome="error").inc()
        # a request the degraded path could not save is a flight-
        # recorder trigger: the live store just failed AND the replica
        # could not cover — the last few seconds of spans/metrics are
        # the diagnosis, and they are about to be evicted. Best-effort,
        # rate-limited, never alters the error the caller sees.
        from ..telemetry import blackbox

        blackbox.trigger_bundle("degraded", detail=f"{reason}: {detail}")
        raise DegradedError(reason, detail) from cause

    def _pull_values(self, keys: np.ndarray) -> np.ndarray:
        """The read path (requests for other channels never get here —
        submit rejects them at the door). Modes:

        - replica full/hot: replica first, coalesced live pull for
          misses; a FAILED live pull degrades (hot misses degrade to
          DegradedError — the hot replica cannot cover them);
        - replica fallback: live-first (fresh reads), replica only as
          the degraded path;
        - replica off: live only; failures are DegradedError(no-replica).
        """
        if self.replica is not None and self.cfg.replica != "fallback":
            vals, hit = self.replica.pull(keys)
            if hit.all():
                return vals
            missed = np.asarray(keys)[~hit]
            try:
                miss_vals = self._live_pull(missed)
            except RejectedError:
                raise
            except Exception as e:
                return self._degraded_fallback(keys, e)
            out = np.array(vals)
            out[~hit] = miss_vals
            return out
        try:
            return self._live_pull(keys)
        except RejectedError:
            raise
        except Exception as e:
            return self._degraded_fallback(keys, e)

    def _execute(self, req):
        if isinstance(req, PullRequest):
            return self._pull_values(req.keys)
        if isinstance(req, PredictRequest):
            w = self._pull_values(req.indices)
            seg = np.repeat(
                np.arange(len(req.indptr) - 1), np.diff(req.indptr)
            )
            margins = np.zeros(len(req.indptr) - 1, np.float64)
            np.add.at(margins, seg, w.sum(axis=1))
            return 1.0 / (1.0 + np.exp(-margins))
        if isinstance(req, DecodeRequest):
            out = np.asarray(self.decode_fn(req))
            tel = self._tel()
            if tel is not None:
                tel["decode_tokens"].inc(out.shape[0] * req.steps)
            return out
        raise TypeError(f"unknown request type {type(req).__name__}")

    # -- replica refresher --

    def _refresh_loop(self) -> None:
        while not self._stop_refresh.wait(self.cfg.replica_refresh_s):
            # the paused check and the _executing claim are ONE critical
            # section: quiesce() waits on _executing, so an in-flight
            # refresh holds the pause→resize sequence back exactly like
            # a worker mid-request does — without this, pause() could
            # pass quiesce() while refresh() is still touching a store
            # the resize is about to tear down
            with self._cv:
                if self._paused:
                    continue
                self._executing += 1
            try:
                self.replica.refresh()
            except Exception:
                # one transient refresh failure must not silently kill
                # the refresher for the rest of the process — the
                # frontend would keep serving an ever-staler snapshot
                # with no signal. Log and retry next tick; persistent
                # failure shows up as a growing replica age_s.
                import logging

                logging.getLogger(__name__).exception(
                    "read-replica refresh failed; retrying next tick"
                )
            finally:
                with self._cv:
                    self._executing -= 1
                    self._cv.notify_all()

    # -- introspection (the serve bench's record fields) --

    def stats(self) -> dict:
        with self._cv:
            completed = self.completed
            in_flight = self._in_flight + self._in_flight_decode
            degraded = self.degraded_served
        out = {
            "completed": completed,
            "in_flight": in_flight,
            "degraded_served": degraded,
            "coalescer": self.coalescer.stats(),
        }
        if self.replica is not None:
            out["replica"] = {
                "version": self.replica.version,
                "age_s": round(self.replica.age_s(), 3),
                "nbytes": self.replica.nbytes(),
                "device": self.replica.device,
            }
        if self.batcher is not None:
            out["batcher"] = self.batcher.stats()
        return out
