"""Open-loop Poisson load generator + latency recorder.

Closed-loop load tests lie about tail latency: when the server slows,
a closed-loop client slows WITH it (it waits for each response before
sending the next request), so the measured p99 flatters the server
exactly when it is failing. Production traffic is open-loop — arrivals
are a Poisson process that does not care how the last request went —
so the bench schedules arrivals from pre-drawn exponential gaps and
fires them on time whether or not earlier requests completed
(coordinated-omission-free: a stalled server faces the full backlog).

``open_loop_bench`` returns the dict the ``serve`` section of every
``bench.py`` record embeds per offered-load point: offered vs accepted
vs completed rates (goodput), shed counts by reason, and
p50/p90/p99/p99.9/max completion latency. Determinism: arrivals come
from ``np.random.default_rng(seed)``; wall-clock scheduling is the only
nondeterminism left (disclosed via ``achieved_offered_rate``).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, List

import numpy as np

from .admission import RejectedError


@dataclasses.dataclass
class LatencyStats:
    n: int
    p50_ms: float
    p90_ms: float
    p99_ms: float
    p999_ms: float
    max_ms: float

    @staticmethod
    def from_seconds(lat_s: "np.ndarray | List[float]") -> "LatencyStats":
        lat = np.asarray(lat_s, dtype=np.float64) * 1e3
        if lat.size == 0:
            return LatencyStats(0, 0.0, 0.0, 0.0, 0.0, 0.0)
        q = np.percentile(lat, [50, 90, 99, 99.9])
        return LatencyStats(
            n=int(lat.size),
            p50_ms=round(float(q[0]), 3),
            p90_ms=round(float(q[1]), 3),
            p99_ms=round(float(q[2]), 3),
            p999_ms=round(float(q[3]), 3),
            max_ms=round(float(lat.max()), 3),
        )

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def open_loop_bench(
    frontend,
    make_request: Callable[[int], object],
    rate: float,
    duration_s: float,
    seed: int = 0,
    collectors: int = 4,
    warmup_requests: int = 0,
) -> dict:
    """Drive ``frontend`` with Poisson arrivals at ``rate`` req/s for
    ``duration_s``; returns the offered-load point's record dict.

    ``make_request(i)`` builds the i-th request (vary keys per call for
    realistic overlap patterns). Completion latencies are collected by
    ``collectors`` waiter threads so slow completions never block the
    arrival schedule (the open-loop contract). ``warmup_requests``
    issues that many requests closed-loop first, excluded from stats
    (compile/caches must not pollute the tail)."""
    for i in range(warmup_requests):
        try:
            frontend.submit(make_request(i)).result(timeout=120)
        except RejectedError:
            pass

    rng = np.random.default_rng(seed)
    n_planned = max(1, int(rate * duration_s * 1.5))
    gaps = rng.exponential(1.0 / rate, size=n_planned)
    arrivals = np.cumsum(gaps)

    tickets: List[object] = []  # guarded-by: tickets_lock
    tickets_lock = threading.Lock()
    done_collecting = threading.Event()
    latencies: List[float] = []  # guarded-by: tickets_lock
    errors: List[str] = []  # guarded-by: tickets_lock

    def collect():
        while True:
            with tickets_lock:
                t = tickets.pop() if tickets else None
            if t is None:
                if done_collecting.is_set():
                    return
                time.sleep(0.0005)
                continue
            try:
                t.result(timeout=120)
                with tickets_lock:
                    latencies.append(t.latency_s())
            except BaseException as e:  # collected, not raised: the
                # bench must report a failing server, not crash on it
                with tickets_lock:
                    errors.append(f"{type(e).__name__}: {e}")

    threads = [
        threading.Thread(target=collect, name=f"serve-collect-{i}",
                         daemon=True)
        for i in range(collectors)
    ]
    for t in threads:
        t.start()

    shed_rate = shed_queue = submitted = 0
    t0 = time.perf_counter()
    for i, due in enumerate(arrivals):
        if due > duration_s:
            break
        now = time.perf_counter() - t0
        if due > now:
            time.sleep(due - now)
        # behind schedule: fire immediately (open-loop catch-up — the
        # arrival process does not thin out because the host is busy)
        try:
            ticket = frontend.submit(make_request(i))
            submitted += 1
            with tickets_lock:
                tickets.append(ticket)
        except RejectedError as e:
            if e.reason == "rate":
                shed_rate += 1
            else:
                shed_queue += 1
    offered = submitted + shed_rate + shed_queue
    elapsed_submit = time.perf_counter() - t0
    done_collecting.set()
    for t in threads:
        t.join(timeout=180)
    elapsed = time.perf_counter() - t0

    stats = LatencyStats.from_seconds(latencies)
    return {
        "offered_rate": round(rate, 1),
        "achieved_offered_rate": round(offered / elapsed_submit, 1),
        "duration_s": round(elapsed, 3),
        "offered": offered,
        "accepted": submitted,
        "completed": stats.n,
        "shed_rate": shed_rate,
        "shed_queue": shed_queue,
        "shed_frac": round((shed_rate + shed_queue) / max(1, offered), 4),
        "goodput_per_sec": round(stats.n / elapsed, 1),
        "latency_ms": stats.as_dict(),
        "errors": errors[:5],
        "n_errors": len(errors),
    }
