"""Serving plane: the request-path frontend over live parameter tables.

The reference parameter server trains AND serves (OSDI'14 §5: "heavy
traffic from millions of users"); PRs 1-5 built only the training half.
This package is the read path: concurrent client sessions issuing
sparse pulls / predictions against KVVector/KVMap tables and LM decode
against the transformer stack, with the three production mechanisms a
latency SLO needs —

- **admission control** (:mod:`.admission`): token-bucket rate limiting
  + queue-depth shedding with explicit 429-style rejection
  (:class:`RejectedError`), so p99 stays bounded under overload instead
  of collapsing into an unbounded queue.
- **request coalescing** (:mod:`.coalescer`): concurrent pulls for
  overlapping key ranges merge into ONE executor submit over the union
  key set (dedup'd host-side, slot mapping served by the KeyDirectory
  signature cache), inside a bounded coalesce window.
- **read replicas** (:mod:`.replica`): snapshot-consistent read copies
  refreshed OFF the push path (the donation-safe ``table(copy=True)``
  contract from the zero-copy data plane), so serving reads never
  contend with — and can never be invalidated by — training pushes.
- **continuous batching** (:mod:`.batcher`): concurrent decode
  sessions share ONE running speculative-decode call, joining at round
  boundaries into free batch slots and retiring between rounds — fleet
  throughput from the batched-matmul weights-read-once property, with
  per-session greedy token parity as the correctness contract.
- **degraded-mode serving** (chaos plane, doc/ROBUSTNESS.md): a live
  pull that fails or misses ``live_pull_deadline_s`` falls back to the
  read replica inside a staleness bound; past it, requests fail with
  the 503-style :class:`DegradedError` — DISTINCT from the admission
  429, so overload shedding and failure degradation are separately
  observable (``ps_serve_degraded_total`` vs ``ps_serve_shed_total``).

:mod:`.frontend` composes them into :class:`ServeFrontend`;
:mod:`.loadgen` is the open-loop Poisson load generator + latency
recorder behind ``make serve-bench`` and the ``serve`` section of every
``bench.py`` record (p50/p99/p99.9 + goodput-vs-offered-load).
"""

from .admission import AdmissionController, RejectedError, TokenBucket
from .batcher import BatcherConfig, ContinuousBatcher
from .coalescer import PullCoalescer
from .frontend import (
    DecodeRequest,
    DegradedError,
    PredictRequest,
    PullRequest,
    ServeConfig,
    ServeFrontend,
)
from .loadgen import LatencyStats, open_loop_bench
from .replica import ReadReplica

__all__ = [
    "AdmissionController",
    "BatcherConfig",
    "ContinuousBatcher",
    "DecodeRequest",
    "DegradedError",
    "LatencyStats",
    "PredictRequest",
    "PullCoalescer",
    "PullRequest",
    "ReadReplica",
    "RejectedError",
    "ServeConfig",
    "ServeFrontend",
    "TokenBucket",
    "open_loop_bench",
]
