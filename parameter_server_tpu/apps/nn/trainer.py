"""Neural-net worker trained through KVLayer dense push/pull.

Role of the reference's CXXNET/Minerva integration: the NN worker computes
layer gradients, pushes them to the KVLayer servers whose Updater applies
the optimizer, and pulls fresh weights each minibatch (kv_layer.h Push/Pull
with partition_thr slicing).

TPU-native: one fused SPMD step — per-data-shard forward/backward inside
``shard_map``, gradient ``psum`` over the data axis (the push), optimizer
update (the server-side Updater), all compiled together. The KVLayer object
remains the parameter store (sharding per its partition threshold) so the
replica/checkpoint machinery applies unchanged.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...utils.compat import shard_map
from jax.sharding import PartitionSpec as P

from ...models.convnet import cross_entropy
from ...parallel import mesh as meshlib
from ...parallel.mesh import DATA_AXIS
from ...parameter.kv_layer import KVLayer
from ...parameter.replica import Checkpointable
from ...system.message import Task


class OptaxUpdater:
    """KVLayer Updater backed by an optax optimizer (server-side optimizer,
    ref KVLayerUpdater::Update)."""

    def __init__(self, tx):
        self.tx = tx
        self.opt_state = None

    def init(self, name, shape, dtype=jnp.float32):
        return jnp.zeros(shape, dtype)

    def init_opt(self, params):
        self.opt_state = self.tx.init(params)

    def update(self, name, weight, recv):  # single-layer path (API parity)
        updates, _ = self.tx.update({name: recv}, self.tx.init({name: weight}), {name: weight})
        return weight + updates[name]


class NNTrainer(Checkpointable):
    def __init__(
        self,
        model,
        input_shape: Tuple[int, ...],
        mesh=None,
        optimizer=None,
        partition_thr: int = 100_000,
        loss_fn: Callable = cross_entropy,
        seed: int = 0,
    ):
        from ...system.postoffice import Postoffice

        import optax

        self.model = model
        self.mesh = mesh if mesh is not None else Postoffice.instance().mesh
        assert self.mesh is not None, "Postoffice.start() first"
        self.tx = optimizer or optax.sgd(0.05, momentum=0.9)
        self.loss_fn = loss_fn
        rng = jax.random.PRNGKey(seed)
        params = model.init(rng, jnp.zeros((1,) + tuple(input_shape)))["params"]
        # KVLayer is the parameter store (sharded per partition threshold)
        self.kv = KVLayer(partition_thr=partition_thr, mesh=self.mesh, name="nn_layers")
        flat = jax.tree_util.tree_leaves_with_path(params)
        self.params = {}
        for path, leaf in flat:
            key = "/".join(str(p.key) for p in path)
            self.kv.layers[key] = jax.device_put(leaf, self.kv._sharding(leaf.shape))
        self._param_struct = jax.tree.structure(params)
        self.opt_state = self.tx.init(self._pack())
        self._step = self._build_step()
        self.steps_done = 0

    def _pack(self):
        # drain in-flight KVLayer pushes first: they donate layer
        # buffers on the store's executor thread (donate=True default),
        # and packing must never read — or feed into the donating train
        # step — a buffer a queued push is about to consume
        self.kv.executor.wait_all(pop=False)
        leaves = [self.kv.layers[k] for k in sorted(self.kv.layers)]
        return jax.tree.unflatten(self._param_struct, leaves)

    def _unpack(self, params) -> None:
        leaves = jax.tree.leaves(params)
        for k, leaf in zip(sorted(self.kv.layers), leaves):
            self.kv.layers[k] = leaf

    def _build_step(self):
        model, loss_fn, tx = self.model, self.loss_fn, self.tx

        def local_step(params, opt_state, x, y):
            x, y = x[0], y[0]

            def loss(p):
                logits = model.apply({"params": p}, x)
                return loss_fn(logits, y), logits

            (lval, logits), grads = jax.value_and_grad(loss, has_aux=True)(params)
            # the KVLayer push: combine worker gradients over the data axis
            grads = jax.lax.pmean(grads, DATA_AXIS)
            updates, new_opt = tx.update(grads, opt_state, params)
            import optax

            new_params = optax.apply_updates(params, updates)
            acc = jnp.mean((jnp.argmax(logits, -1) == y).astype(jnp.float32))
            metrics = {
                "loss": jax.lax.pmean(lval, DATA_AXIS),
                "accuracy": jax.lax.pmean(acc, DATA_AXIS),
            }
            return new_params, new_opt, metrics

        import functools

        # the trainer owns params (the KVLayer arrays it re-installs via
        # _unpack) and opt_state, and replaces both every step — donate
        # them so the fused step updates weights/momenta in place instead
        # of materializing a full parameter copy per step (the KVLayer
        # donation contract; checkpoints copy to host first)
        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def step(params, opt_state, x, y):
            specs = jax.tree.map(lambda _: P(), params)
            opt_specs = jax.tree.map(lambda _: P(), opt_state)
            return shard_map(
                local_step,
                mesh=self.mesh,
                in_specs=(specs, opt_specs, P(DATA_AXIS), P(DATA_AXIS)),
                out_specs=(specs, opt_specs, P()),
                check_vma=False,
            )(params, opt_state, x, y)

        return step

    def state_host(self) -> dict:
        """HOST-ARRAY snapshot for checkpoint/restore and live migration
        (the Checkpointable/ElasticCoordinator hook pair — same contract
        as the linear/FM/DeepCTR workers: numpy out, resharded in)."""
        return jax.tree.map(
            np.asarray,
            {
                "params": self._pack(),
                "opt": self.opt_state,
                "steps_done": np.int64(self.steps_done),
            },
        )

    def load_state_host(self, snap: dict) -> None:
        # params back onto the KVLayer's partition-threshold shardings;
        # optimizer leaves as uncommitted host arrays (jit re-places them
        # alongside the params on the next step)
        placed = jax.tree.map(
            lambda leaf: jax.device_put(
                np.asarray(leaf), self.kv._sharding(np.shape(leaf))
            ),
            snap["params"],
        )
        self._unpack(placed)
        self.opt_state = jax.tree.map(np.asarray, snap["opt"])
        self.steps_done = int(snap["steps_done"])

    # checkpoint/restore: inherited from replica.Checkpointable

    def shard_batch(self, x: np.ndarray, y: np.ndarray):
        d = meshlib.num_workers(self.mesh)
        n = len(y)
        per = n // d
        assert per * d == n, f"batch {n} not divisible by {d} workers"
        xs = x.reshape((d, per) + x.shape[1:]).astype(np.float32)
        ys = y.reshape(d, per).astype(np.int32)
        sh = meshlib.batch_sharding(self.mesh)
        return jax.device_put(xs, sh), jax.device_put(ys, sh)

    def train_step(self, x: np.ndarray, y: np.ndarray) -> Dict[str, float]:
        xs, ys = self.shard_batch(x, y)
        params = self._pack()
        new_params, self.opt_state, metrics = self._step(params, self.opt_state, xs, ys)
        self._unpack(new_params)
        self.steps_done += 1
        return {k: float(v) for k, v in metrics.items()}

    def evaluate(self, x: np.ndarray, y: np.ndarray) -> Dict[str, float]:
        logits = self.model.apply({"params": self._pack()}, jnp.asarray(x, jnp.float32))
        acc = float(jnp.mean((jnp.argmax(logits, -1) == jnp.asarray(y)).astype(jnp.float32)))
        loss = float(self.loss_fn(logits, jnp.asarray(y)))
        return {"accuracy": acc, "loss": loss}

    # -- KVLayer API parity passthroughs --

    def push(self, key, grad, task: Optional[Task] = None) -> int:
        return self.kv.push(task or self.kv.request(), key, grad)

    def pull(self, key, task: Optional[Task] = None):
        return self.kv.wait_pull(self.kv.pull(task or self.kv.request(), key))

    def push_pull(self, key, grad, task: Optional[Task] = None):
        """Fused gradient push + weight refresh: one submitted step
        returns the post-update layer (KVLayer.push_pull) — the worker's
        push-then-pull-same-key round trip in a single dispatch."""
        return self.kv.wait_pull(
            self.kv.push_pull(task or self.kv.request(), key, grad)
        )
