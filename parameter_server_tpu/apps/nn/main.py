"""NN-through-KVLayer CLI (the reference's CXXNET/Minerva guinea pig,
README "KVLayer" integration: a conv net whose layers live in the
parameter server):

    python -m parameter_server_tpu.apps.nn.main \
        [--model mlp|convnet] [--steps N] [--batch B] [--num-servers S]

Trains on synthetic data (blobs for the MLP, random images for the conv
net) so it runs anywhere; prints per-interval loss/accuracy like the
reference's progress rows.
"""

from __future__ import annotations

import argparse
import sys

import numpy as np


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model", choices=("mlp", "convnet"), default="mlp")
    ap.add_argument("--steps", type=int, default=50)
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--classes", type=int, default=10)
    ap.add_argument("--num-servers", type=int, default=1)
    ap.add_argument("--report-every", type=int, default=10)
    args = ap.parse_args(argv)

    from ...models.convnet import ConvNet, MLP
    from ...system.postoffice import Postoffice
    from .trainer import NNTrainer

    po = Postoffice.instance().start(num_server=args.num_servers)

    rng = np.random.default_rng(0)
    if args.model == "convnet":
        model = ConvNet(num_classes=args.classes)
        input_shape = (16, 16, 3)
        centers = rng.normal(size=(args.classes,) + input_shape).astype(np.float32)

        def batch():
            y = rng.integers(0, args.classes, args.batch).astype(np.int32)
            x = centers[y] + 0.5 * rng.normal(size=(args.batch,) + input_shape)
            return x.astype(np.float32), y
    else:
        model = MLP(num_classes=args.classes)
        input_shape = (32,)
        centers = rng.normal(size=(args.classes, 32)).astype(np.float32)

        def batch():
            y = rng.integers(0, args.classes, args.batch).astype(np.int32)
            x = centers[y] + 0.5 * rng.normal(size=(args.batch, 32))
            return x.astype(np.float32), y

    trainer = NNTrainer(model, input_shape=input_shape, mesh=po.mesh)
    print(f"{'step':>5} {'loss':>9} {'accuracy':>9}")
    for step in range(1, args.steps + 1):
        x, y = batch()
        m = trainer.train_step(x, y)
        if step % args.report_every == 0 or step == args.steps:
            print(f"{step:>5} {m['loss']:>9.5f} {m['accuracy']:>9.4f}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
