"""App factory (ref ``src/app/linear_method/main.cc`` App::Create dispatch):
pick the app from which config sections are present — darlin > async_sgd >
validation-only (model evaluation)."""

from __future__ import annotations

from ..system.customer import App
from .linear.config import Config


def create_app(conf: Config) -> App:
    if conf.darlin is not None:
        from .linear.darlin import DarlinScheduler

        return DarlinScheduler(conf)
    if conf.async_sgd is not None:
        from .linear.async_sgd import AsyncSGDScheduler

        return AsyncSGDScheduler(conf)
    if conf.validation_data is not None:
        from .linear.model_evaluation import ModelEvaluation

        return ModelEvaluation(conf)
    raise ValueError("config selects no app (need darlin/async_sgd/validation_data)")
