"""Serving CLI — stand up the request-path frontend over a live table
(and optionally an LM) and drive it with open-loop Poisson load:

    python -m parameter_server_tpu.apps.serve.main \
        [--num-slots N] [--keys-per-request K] [--workers W] \
        [--rate R | --rate-multiplier M] [--duration S] \
        [--admission-rate R] [--max-queue-depth D] \
        [--coalesce-window-ms MS] [--replica full|hot|off] \
        [--train-while-serving] [--decode] [--gamma G] [--json]

The serving analog of apps/lm's train-and-generate CLI: it synthesizes
a trained-looking FTRL weight table (KVVector, hashed directory),
wraps it in a :class:`~parameter_server_tpu.serving.ServeFrontend`
(admission control → worker pool → read replica → request coalescing),
and reports p50/p99/p99.9 + goodput per offered-load point as JSON
lines — the same record shape ``make serve-bench`` and ``bench.py``'s
``serve`` section emit (doc/SERVING.md has the knob guide).

``--train-while-serving`` streams concurrent donated pushes into the
live table from a background thread while the load runs — the
demonstration that replica-served reads never contend with (or get
invalidated by) the training push path. ``--decode`` adds a
speculative-decoding LM lane; ``--draft trained`` trains the
(target, draft) byte-model pair on the structured corpus the
``spec_big`` on-chip bench uses (script/onchip.py: 2.33x at gamma=8,
accepted 0.978 on the 860M target), so the reported acceptance rate
reflects a draft that actually tracks its target instead of the
random-init wiring models. ``--batch-slots N`` serves the decode lane
through the continuous batcher (serving/batcher.py) instead of one
sequential call per request.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time

import numpy as np


def _spec_corpus(rng):
    """The structured byte corpus every speculative bench shares
    (script/onchip.py _spec_corpus): a 16-byte cycle with 10% uniform
    noise — regular enough that a tiny draft tracks the target, noisy
    enough that losses stay informative."""
    pat = np.tile(np.arange(97, 113, dtype=np.int32), 1 << 12)
    noise = rng.integers(0, 256, pat.size, np.int32)
    return np.where(rng.random(pat.size) < 0.1, noise, pat)


def _decode_models(draft: str, seed: int):
    """The decode lane's (target, draft) pair. ``draft="random"`` is
    the old wiring (random-init weights, acceptance ~1/vocab);
    ``draft="trained"`` trains both models on the spec_big corpus —
    CPU-scaled shapes of the measured on-chip config — so the
    frontend's acceptance rate means something."""
    import jax

    from ...models.transformer import LMConfig, init_lm

    tcfg = LMConfig(vocab=256, d_model=64, n_heads=4, n_layers=2, d_ff=128)
    dcfg = LMConfig(vocab=256, d_model=32, n_heads=2, n_layers=1, d_ff=64)
    tparams = init_lm(jax.random.PRNGKey(0), tcfg)
    dparams = init_lm(jax.random.PRNGKey(1), dcfg)
    info = {"draft": draft}
    if draft == "trained":
        from ...parallel.mesh import make_mesh
        from ...models.transformer import make_lm_train_step, shard_tokens

        mesh = make_mesh()
        rng = np.random.default_rng(seed)
        corpus = _spec_corpus(rng)
        seq = 64
        losses = {}
        # lr-per-width + enough steps that the pair actually converges
        # on the cycle (undertrained pairs quote accepted_frac ~0 and
        # defeat the point of --draft trained; this recipe lands
        # ~0.85-0.9 in ~15s of CPU)
        for nm, cfg_i, p_i, lr_i, nst in (
            ("target", tcfg, tparams, 0.2, 300),
            ("draft", dcfg, dparams, 0.4, 200),
        ):
            step_i = make_lm_train_step(cfg_i, mesh, lr=lr_i)
            tl = None
            for _ in range(nst):
                starts = rng.integers(0, corpus.size - seq - 1, 8)
                toks = np.stack([corpus[s:s + seq + 1] for s in starts])
                p_i, tl = step_i(p_i, shard_tokens(toks, mesh))
            if not np.isfinite(float(tl)):
                raise RuntimeError(
                    f"--draft trained: {nm} training diverged "
                    f"(loss={float(tl)})"
                )
            losses[f"{nm}_loss"] = round(float(tl), 3)
            if nm == "target":
                tparams = p_i
            else:
                dparams = p_i
        info.update(losses)
    return tparams, tcfg, dparams, dcfg, info


def _decode_lane(gamma: int, models):
    """A speculative-decoding decode_fn over the pair from
    :func:`_decode_models` (sequential: one call per request)."""
    import jax

    from ...models.speculative import speculative_generate

    tparams, tcfg, dparams, dcfg, _ = models

    def decode_fn(req):
        return speculative_generate(
            tparams, tcfg, dparams, dcfg,
            jax.numpy.asarray(req.prompt, jax.numpy.int32), req.steps,
            gamma=gamma, eos_id=req.eos_id,
        )

    return decode_fn


def main(argv=None) -> int:
    from ...parallel.mesh import honor_jax_platforms

    honor_jax_platforms()
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--num-slots", type=int, default=1 << 18)
    ap.add_argument("--key-space", type=int, default=1 << 24)
    ap.add_argument("--keys-per-request", type=int, default=32)
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--rate", type=float, default=0.0,
                    help="offered load, requests/s (0 = calibrate)")
    ap.add_argument("--rate-multiplier", type=float, nargs="*",
                    default=[0.25, 3.0],
                    help="offered-load points as multiples of the "
                    "calibrated closed-loop capacity (used when --rate "
                    "is 0)")
    ap.add_argument("--duration", type=float, default=3.0)
    ap.add_argument("--admission-rate", type=float, default=-1.0,
                    help="token-bucket accept rate (requests/s); -1 = "
                    "0.6x calibrated capacity, 0 = no rate gate")
    ap.add_argument("--max-queue-depth", type=int, default=64)
    ap.add_argument("--coalesce-window-ms", type=float, default=2.0)
    ap.add_argument("--replica", default="full",
                    choices=("full", "hot", "off"))
    ap.add_argument("--hot-fraction", type=float, default=0.01,
                    help="fraction of the key space snapshotted by the "
                    "hot replica, capped at the request pool's distinct "
                    "keys (--replica hot)")
    ap.add_argument("--train-while-serving", action="store_true",
                    help="stream donated pushes into the live table "
                    "while serving (replica isolation demo)")
    ap.add_argument("--decode", action="store_true",
                    help="add the speculative-decode LM lane")
    ap.add_argument("--draft", default="random",
                    choices=("random", "trained"),
                    help="decode-lane model pair: random-init wiring "
                    "models, or a pair trained on the spec_big corpus "
                    "so acceptance reflects the measured config")
    ap.add_argument("--batch-slots", type=int, default=0,
                    help="serve decode through the continuous batcher "
                    "with this many slots (0 = sequential decode_fn)")
    ap.add_argument("--gamma", type=int, default=4)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--expose-port", type=int, default=None, metavar="PORT",
                    help="serve the cluster metrics plane while the CLI "
                    "runs (telemetry/exposition.py): /metrics (node-"
                    "labeled aggregate incl. the live ps_serve_* "
                    "family), /healthz, /debug/snapshot; the default "
                    "SLO alert rules evaluate against this process — "
                    "overload points past the serve p99 rule show "
                    "ps_alert_state flip live. 0 = ephemeral")
    args = ap.parse_args(argv)

    from ...parameter.kv_vector import KVVector
    from ...serving import (
        DecodeRequest,
        PullRequest,
        ServeConfig,
        ServeFrontend,
        open_loop_bench,
    )
    from ...system.postoffice import Postoffice

    Postoffice.reset()
    po = Postoffice.instance().start()
    exposition = None
    if args.expose_port is not None:
        from ...telemetry.exposition import expose_cluster

        exposition = expose_cluster(
            po, port=args.expose_port, metrics_interval=1.0
        )
        print(f"serve: metrics exposed at {exposition.url}/metrics "
              f"(/healthz, /debug/snapshot)", file=sys.stderr)
    kv = KVVector(
        mesh=po.mesh, k=1, num_slots=args.num_slots, hashed=True,
        name="serve_w",
    )
    rng = np.random.default_rng(args.seed)
    warm = np.unique(rng.integers(0, args.key_space, 1 << 14))
    kv.wait(kv.push(
        kv.request(channel=0), keys=warm,
        values=rng.normal(size=(len(warm), 1)).astype(np.float32),
    ))

    u = rng.random((512, args.keys_per_request))
    pool = (u * u * u * args.key_space).astype(np.int64)  # power-law keys

    def make_request(i: int):
        return PullRequest(keys=pool[i % len(pool)])

    hot_keys = None
    if args.replica == "hot":
        # the hot set is the HEAD of the actual request-key pool (most
        # frequent keys first) — an independent random draw over the
        # 2^24 key space would miss nearly every requested key and demo
        # only the fallthrough path instead of a hot working set
        uniq, counts = np.unique(pool, return_counts=True)
        n_hot = max(1, min(len(uniq), int(args.hot_fraction * args.key_space)))
        hot_keys = uniq[np.argsort(counts, kind="stable")[::-1][:n_hot]]

    models = _decode_models(args.draft, args.seed) if args.decode else None

    def make_batcher():
        from ...serving import BatcherConfig, ContinuousBatcher

        tparams, tcfg, dparams, dcfg, _ = models
        return ContinuousBatcher(
            tparams, tcfg, dparams, dcfg,
            BatcherConfig(
                slots=args.batch_slots, max_prompt=64, max_new=64,
                gamma=args.gamma,
            ),
        )

    def build(admission_rate: float) -> ServeFrontend:
        batched = args.decode and args.batch_slots > 0
        return ServeFrontend(
            kv,
            ServeConfig(
                admission_rate=max(0.0, admission_rate),
                admission_burst=max(1.0, admission_rate / 10),
                max_queue_depth=args.max_queue_depth,
                coalesce_window_s=args.coalesce_window_ms / 1e3,
                replica=args.replica,
                hot_keys=hot_keys,
                workers=args.workers,
            ),
            decode_fn=(
                _decode_lane(args.gamma, models)
                if args.decode and not batched else None
            ),
            batcher=make_batcher() if batched else None,
        ).start()

    def emit(rec: dict) -> None:
        print(json.dumps(rec), flush=True)

    # calibrate capacity closed-loop
    fe = build(0.0)
    for i in range(10):
        fe.submit(make_request(i)).result(30)
    n_cal = 200
    t0 = time.perf_counter()
    for i in range(n_cal):
        fe.submit(make_request(i)).result(30)
    capacity = n_cal / (time.perf_counter() - t0)
    emit({"metric": "serve_closed_loop_capacity", "value": round(capacity, 1),
          "unit": "requests/sec", "replica": args.replica,
          "workers": args.workers})
    fe.close()

    admission = (
        0.6 * capacity if args.admission_rate < 0 else args.admission_rate
    )
    fe = build(admission)

    stop_training = threading.Event()
    trainer = None
    if args.train_while_serving:
        def train_loop():
            i = 0
            while not stop_training.is_set():
                keys = pool[i % len(pool)]
                kv.wait(kv.push(
                    kv.request(channel=0), keys=np.unique(keys),
                    values=np.ones((len(np.unique(keys)), 1), np.float32),
                ))
                i += 1
        trainer = threading.Thread(
            target=train_loop, name="serve-trainer", daemon=True
        )
        trainer.start()

    rates = (
        [args.rate] if args.rate > 0
        else [m * capacity for m in args.rate_multiplier]
    )
    for rate in rates:
        rec = open_loop_bench(
            fe, make_request, rate=rate, duration_s=args.duration,
            seed=args.seed, warmup_requests=5,
        )
        rec["metric"] = "serve_open_loop_point"
        rec["admission_rate"] = round(admission, 1)
        rec["train_while_serving"] = bool(trainer)
        emit(rec)

    if args.decode:
        from ...serving import RejectedError

        def submit_decode(req, deadline_s: float = 30.0):
            # the open-loop overload points just drained the token
            # bucket, so the first decode submits can legitimately see
            # the 429 — honor retry_after_s instead of crashing the CLI
            # on the rejection the subsystem explicitly models
            t_end = time.monotonic() + deadline_s
            while True:
                try:
                    return fe.submit(req)
                except RejectedError as e:
                    if time.monotonic() >= t_end:
                        raise
                    time.sleep(max(e.retry_after_s, 0.05))

        if args.draft == "trained":
            # prompts FROM the corpus the pair was trained on — an
            # acceptance rate quoted on uniform-random bytes would
            # measure the noise floor, not the draft
            corpus = _spec_corpus(np.random.default_rng(args.seed))
            starts = rng.integers(0, corpus.size - 32, 4)
            prompt = np.stack(
                [corpus[s:s + 32] for s in starts]
            ).astype(np.int32)
        else:
            prompt = rng.integers(0, 256, (4, 32)).astype(np.int32)
        t = submit_decode(DecodeRequest(prompt=prompt, steps=32))
        t.result(600)  # compile
        lat = []
        for _ in range(3):
            t = submit_decode(DecodeRequest(prompt=prompt, steps=32))
            t.result(600)
            lat.append(t.latency_s())
        # acceptance measured on the served pair directly (the number
        # that decides whether the draft pays for itself; ~0 for
        # --draft random, high for --draft trained)
        from ...models.speculative import speculative_generate

        tparams, tcfg, dparams, dcfg, draft_info = models
        _, spec_stats = speculative_generate(
            tparams, tcfg, dparams, dcfg, prompt, 32, gamma=args.gamma,
            return_stats=True,
        )
        rec = {
            "metric": "serve_decode_latency_ms",
            "value": round(float(np.median(lat)) * 1e3, 1),
            "unit": "ms", "gamma": args.gamma,
            "tokens_per_request": int(prompt.shape[0]) * 32,
            "accepted_frac": round(float(spec_stats["accepted_frac"]), 3),
            **draft_info,
        }
        if args.batch_slots > 0:
            rec["batcher"] = fe.batcher.stats()
        emit(rec)

    if trainer is not None:
        stop_training.set()
        trainer.join(timeout=60)
    emit({"metric": "serve_frontend_stats", "value": 1, "unit": "ok",
          **fe.stats()})
    fe.close()
    if exposition is not None:
        ok, health = exposition.aux.health()
        emit({"metric": "serve_exposition", "value": 1, "unit": "ok",
              "url": exposition.url, "healthz_ok": ok,
              "alerts_firing": health.get("alerts_firing", [])})
        from ...telemetry.exposition import close_cluster

        close_cluster(exposition)
    return 0


if __name__ == "__main__":
    sys.exit(main())
