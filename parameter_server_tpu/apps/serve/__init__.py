"""Serving app: the request-path CLI over the serving plane."""
