"""Sequence-parallel language-model app (training + generation CLI)."""
