"""Byte-level LM CLI — train the sequence-parallel transformer on a text
file (or a built-in synthetic corpus) and generate from it:

    python -m parameter_server_tpu.apps.lm.main \
        [--data FILE] [--steps N] [--seq-len S] [--batch B] \
        [--attention ring|ring_flash|ring_zigzag|a2a] [--window W] \
        [--remat] [--bf16] [--moe-every K] [--num-servers T] \
        [--ckpt-dir DIR] [--save-every N] [--resume] \
        [--prompt "text"] [--gen-tokens N] [--temperature T] [--top-k K] \
        [--top-p P] [--n-kv-heads G]

The model family's end-to-end surface, like apps/linear (conf CLI) and
apps/nn: tokens are raw bytes (vocab 256, no tokenizer dependency), the
sequence axis shards over every available device, and every parallelism/
memory knob of models/transformer.py is reachable from the command line.
Without --data it trains on a synthetic periodic-byte corpus so the demo
runs anywhere.
"""

from __future__ import annotations

import argparse
import functools
import sys

import numpy as np


def _load_corpus(path: str | None, rng: np.random.Generator) -> np.ndarray:
    """The training byte stream. Synthetic fallback: a periodic pattern
    with noise — learnable only by attending a full period back."""
    if path:
        data = np.frombuffer(open(path, "rb").read(), np.uint8)
        if data.size < 1 << 12:
            print(f"warning: tiny corpus ({data.size} bytes)", file=sys.stderr)
        return data
    base = rng.integers(0, 256, 64, dtype=np.uint8)
    reps = np.tile(base, 4096)
    noise = rng.integers(0, 256, reps.size, dtype=np.uint8)
    return np.where(rng.random(reps.size) < 0.02, noise, reps)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--data", default=None, help="text/bytes file (default: synthetic)")
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--seq-len", type=int, default=256)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--d-model", type=int, default=64)
    ap.add_argument("--n-heads", type=int, default=4)
    ap.add_argument(
        "--n-kv-heads", type=int, default=None,
        help="grouped-query attention: K/V heads (< n-heads shrinks the "
        "decode KV cache by the group factor; 1 = MQA; default: n-heads)",
    )
    ap.add_argument("--n-layers", type=int, default=2)
    ap.add_argument("--d-ff", type=int, default=128)
    ap.add_argument(
        "--attention", default="ring_flash",
        choices=("ring", "ring_flash", "ring_zigzag", "a2a"),
        help="sequence-parallel schedule (default ring_flash: measured "
        "1.45x over the XLA chunk path on v5e, BENCH_ONCHIP.md)",
    )
    ap.add_argument("--window", type=int, default=None,
                    help="sliding-window span (flash modes)")
    ap.add_argument("--rope", action="store_true",
                    help="rotary position embeddings (parameter-free "
                    "relative positions; default is NoPE)")
    ap.add_argument("--rope-theta", type=float, default=10000.0)
    ap.add_argument("--remat", action="store_true",
                    help="rematerialize layers (jax.checkpoint)")
    ap.add_argument("--bf16", action="store_true",
                    help="bfloat16 decoder activations")
    ap.add_argument("--moe-every", type=int, default=0)
    ap.add_argument("--zero1", action="store_true",
                    help="ZeRO-1: shard Adam moments over the data axis "
                    "(per-device optimizer memory / n_data; composes "
                    "with --num-servers tensor parallelism)")
    ap.add_argument("--fsdp", action="store_true",
                    help="FSDP/ZeRO-3: shard the parameters themselves "
                    "over the data axis (grads and Adam moments inherit "
                    "it) — per-device param+grad+optimizer memory / "
                    "n_data; GSPMD all-gathers weights at use and "
                    "reduce-scatters grads; composes with --num-servers "
                    "and --zero1 is implied for the moments")
    ap.add_argument("--kv-cache", choices=("auto", "int8"), default="auto",
                    help="decode KV-cache storage: auto = the compute "
                    "dtype; int8 = per-token quantized cache (half of "
                    "bf16's traffic again; decode is cache-bandwidth-"
                    "bound under GQA). Generation only — training is "
                    "unaffected")
    ap.add_argument("--log-file", metavar="PATH", default=None,
                    help="append one JSON line per report interval "
                    "(step, loss, bits/byte, eval loss when measured, "
                    "tokens/sec, wall time) — machine-readable training "
                    "telemetry beside the printed table")
    ap.add_argument("--profile", metavar="DIR", default=None,
                    help="capture a jax.profiler device trace of the "
                    "training loop into DIR (TensorBoard profile / "
                    "Perfetto format)")
    ap.add_argument("--num-servers", type=int, default=1,
                    help="tensor-parallel axis size: LM weights Megatron-"
                    "split over a 'server' mesh axis (sp x tp on one 2-D "
                    "mesh); must divide the device count")
    ap.add_argument("--optimizer", choices=("adam", "adafactor", "lion"),
                    default="adam",
                    help="adam (default; 2 f32 moments/param), adafactor "
                    "(factored second moment — rows+cols instead of a "
                    "full moment tensor, the low-memory choice beside "
                    "--zero1/--fsdp), or lion (sign momentum, 1 moment)")
    ap.add_argument("--lr", type=float, default=3e-3)
    ap.add_argument("--warmup", type=int, default=0,
                    help="linear LR warmup steps, then cosine decay to "
                    "10%% of --lr by --steps (0 = constant LR)")
    ap.add_argument("--clip-norm", type=float, default=None,
                    help="global-norm gradient clipping")
    ap.add_argument("--grad-accum", type=int, default=1,
                    help="average N microbatch gradients per optimizer "
                    "step (optax.MultiSteps); effective batch = "
                    "--batch * N with unchanged memory per forward")
    ap.add_argument("--eval-every", type=int, default=0,
                    help="evaluate held-out loss every N steps (holds "
                    "out the corpus tail; see --eval-frac)")
    ap.add_argument("--eval-frac", type=float, default=0.1,
                    help="fraction of the corpus tail held out for "
                    "--eval-every (never trained on)")
    ap.add_argument(
        "--steps-per-launch", type=int, default=1,
        help="fuse N sequential optimizer steps into one compiled launch "
        "(lax.scan carries params+opt; identical training trajectory, "
        "N-1 fewer dispatch round trips — the lever for high-latency "
        "links); must divide --steps and --save-every",
    )
    ap.add_argument("--report-every", type=int, default=10)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--ckpt-dir", default=None,
                    help="checkpoint directory (enables save/resume)")
    ap.add_argument("--save-every", type=int, default=0,
                    help="checkpoint every N steps (ref "
                    "save_model_every_n_iter; needs --ckpt-dir)")
    ap.add_argument("--resume", action="store_true",
                    help="resume from the latest checkpoint in --ckpt-dir")
    ap.add_argument("--prompt", default=None,
                    help="generate after training from this text")
    ap.add_argument("--gen-tokens", type=int, default=64)
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--top-k", type=int, default=None)
    ap.add_argument(
        "--top-p", type=float, default=None,
        help="nucleus sampling: keep the smallest probability mass >= "
        "top-p (composes with --top-k; needs --temperature > 0)",
    )
    ap.add_argument(
        "--beam", type=int, default=0, metavar="W",
        help="beam search with W beams instead of greedy/sampled "
        "decoding (prints the best beam; deterministic — ignores "
        "--temperature/--top-k/--top-p)",
    )
    ap.add_argument(
        "--eos-byte", type=int, default=None, metavar="B",
        help="stop-token byte: a generation that emits byte B freezes "
        "('eos then pads'); works with greedy/sampled and --beam",
    )
    args = ap.parse_args(argv)

    from ...parallel.mesh import honor_jax_platforms

    honor_jax_platforms()

    import jax
    import optax

    from ...models.transformer import (
        LMConfig,
        init_lm,
        lm_generate,
        lm_loss,
        lm_loss_with_targets,
        shard_lm_params,
        shard_tokens,
        zigzag_lm_arrays,
    )
    from ...parallel import mesh as meshlib

    n_dev = len(jax.devices())
    if args.num_servers < 1 or n_dev % args.num_servers:
        ap.error(
            f"--num-servers {args.num_servers} must divide the device "
            f"count ({n_dev})"
        )
    n_data = n_dev // args.num_servers
    mesh = meshlib.make_mesh(num_data=n_data, num_server=args.num_servers)
    try:
        cfg = LMConfig(
            vocab=256, d_model=args.d_model, n_heads=args.n_heads,
            n_layers=args.n_layers, d_ff=args.d_ff, attention=args.attention,
            window=args.window, remat=args.remat,
            compute_dtype="bfloat16" if args.bf16 else "float32",
            moe_every=args.moe_every, n_kv_heads=args.n_kv_heads,
            rope=args.rope, rope_theta=args.rope_theta,
            kv_cache_dtype=None if args.kv_cache == "auto" else args.kv_cache,
        )
    except ValueError as e:
        # LMConfig rejects invalid combinations (e.g. --window with
        # --attention a2a); surface them as flag errors, not tracebacks
        ap.error(str(e))
    zig = args.attention == "ring_zigzag"
    if args.seq_len % (2 * n_data if zig else n_data):
        ap.error(f"--seq-len must divide by {2 * n_data if zig else n_data}")
    if args.attention == "a2a" and args.n_heads % n_data:
        ap.error(
            f"--attention a2a needs --n-heads divisible by the "
            f"{n_data}-device data axis (got {args.n_heads})"
        )
    # fail flag mistakes BEFORE the training loop, not after it
    if args.temperature < 0:
        ap.error(f"--temperature must be >= 0, got {args.temperature}")
    if args.top_k is not None:
        if args.temperature == 0:
            ap.error("--top-k requires --temperature > 0 (sampling)")
        if not 1 <= args.top_k <= 256:
            ap.error(f"--top-k must be in [1, 256], got {args.top_k}")
    if args.top_p is not None:
        if args.temperature == 0:
            ap.error("--top-p requires --temperature > 0 (sampling)")
        if not 0.0 < args.top_p <= 1.0:
            ap.error(f"--top-p must be in (0, 1], got {args.top_p}")
    spl = args.steps_per_launch
    if spl < 1:
        ap.error(f"--steps-per-launch must be >= 1, got {spl}")
    if spl > 1:
        if args.steps % spl:
            ap.error(
                f"--steps-per-launch {spl} must divide --steps {args.steps}"
            )
        if args.save_every and args.save_every % spl:
            ap.error(
                f"--steps-per-launch {spl} must divide --save-every "
                f"{args.save_every} (checkpoints land on launch boundaries)"
            )

    rng = np.random.default_rng(args.seed)
    corpus = _load_corpus(args.data, rng)
    if corpus.size <= args.seq_len + 1:
        ap.error(
            f"corpus has {corpus.size} bytes but --seq-len {args.seq_len} "
            "needs at least seq_len+2"
        )
    if args.grad_accum < 1:
        ap.error(f"--grad-accum must be >= 1, got {args.grad_accum}")
    if args.grad_accum > args.steps:
        ap.error(
            f"--grad-accum {args.grad_accum} exceeds --steps "
            f"{args.steps}: no accumulation window would ever complete, "
            "so the model would never update"
        )
    if args.steps % args.grad_accum:
        ap.error(
            f"--grad-accum {args.grad_accum} must divide --steps "
            f"{args.steps}: a trailing partial window would compute "
            "gradients that never reach the optimizer"
        )
    if args.clip_norm is not None and args.clip_norm <= 0:
        ap.error(f"--clip-norm must be > 0, got {args.clip_norm}")
    if args.warmup and args.warmup >= args.steps:
        ap.error(
            f"--warmup {args.warmup} must be < --steps {args.steps}"
        )
    if args.eval_every < 0:
        ap.error(f"--eval-every must be >= 0, got {args.eval_every}")
    eval_corpus = None
    if args.eval_every:
        if not 0.0 < args.eval_frac < 1.0:
            ap.error(f"--eval-frac must be in (0, 1), got {args.eval_frac}")
        split = int(corpus.size * (1.0 - args.eval_frac))
        corpus, eval_corpus = corpus[:split], corpus[split:]
        if min(corpus.size, eval_corpus.size) <= args.seq_len + 1:
            ap.error(
                f"--eval-frac {args.eval_frac} leaves a split too small "
                f"for --seq-len {args.seq_len} "
                f"(train {corpus.size} / eval {eval_corpus.size} bytes)"
            )
    from jax.sharding import NamedSharding, PartitionSpec

    params = init_lm(jax.random.PRNGKey(args.seed), cfg)
    if args.num_servers > 1:
        # Megatron column/row placement; GSPMD inserts the psums and the
        # adam update preserves the sharding
        params = shard_lm_params(params, mesh, "server")
    else:
        # explicitly REPLICATED over the mesh (not an uncommitted
        # single-device default): checkpoint restore places leaves onto
        # the template's sharding, so the template must carry the real
        # training placement or a resumed run would train mis-placed
        params = jax.device_put(params, NamedSharding(mesh, PartitionSpec()))
    if args.fsdp:
        from ...models.transformer import fsdp_shard_lm_params

        # ZeRO-3: params (and, via tx.init inheritance, grads + moments)
        # sharded over the data axis; composes with --num-servers (TP
        # leaves keep their server dim and gain the data axis elsewhere)
        params = fsdp_shard_lm_params(params, mesh, "data")
    # LR schedule -> clip -> adam -> (optional) microbatch accumulation.
    # The schedule/accumulation counters live in the optimizer state, so
    # checkpoint resume continues the schedule where it left off.
    lr_sched = (
        optax.warmup_cosine_decay_schedule(
            init_value=0.0, peak_value=args.lr,
            warmup_steps=max(1, args.warmup // args.grad_accum),
            decay_steps=max(2, args.steps // args.grad_accum),
            end_value=0.1 * args.lr,
        )
        if args.warmup
        else args.lr
    )
    chain = []
    if args.clip_norm:
        chain.append(optax.clip_by_global_norm(args.clip_norm))
    if args.optimizer == "adafactor":
        # factored second moment: the per-param optimizer state is
        # O(rows+cols), the low-memory choice beside --zero1/--fsdp
        chain.append(optax.adafactor(learning_rate=lr_sched))
    elif args.optimizer == "lion":
        chain.append(optax.lion(lr_sched))
    else:
        chain.append(optax.adam(lr_sched))
    tx = optax.chain(*chain)
    if args.grad_accum > 1:
        # each CLI "step" is one microbatch; the inner optimizer (and
        # its schedule) advances every grad_accum-th
        tx = optax.MultiSteps(tx, every_k_schedule=args.grad_accum)
    opt = tx.init(params)  # zeros_like inherits each param's placement
    if args.zero1:
        from ...models.transformer import zero1_shard_opt_state

        # ZeRO-1: moments sharded over the data axis (every leaf comes
        # back mesh-committed, scalars replicated)
        opt = zero1_shard_opt_state(opt, mesh, "data")
    else:
        # freshly-created leaves (adam's step count) aren't mesh-placed —
        # pin them replicated so the restore template is fully committed
        opt = jax.tree.map(
            lambda x: x
            if isinstance(getattr(x, "sharding", None), NamedSharding)
            else jax.device_put(x, NamedSharding(mesh, PartitionSpec())),
            opt,
        )

    mgr = None
    start_step = 0
    if args.ckpt_dir:
        from ...parameter.replica import CheckpointManager

        mgr = CheckpointManager(args.ckpt_dir)
        if args.resume:
            latest = mgr.latest_step()
            if latest is not None:
                tree = mgr.restore(
                    latest, like={"params": params, "opt": opt}
                )
                # restore device_puts every leaf onto the template's
                # sharding — which carries the real training placement
                # (replicated, or Megatron-split under --num-servers)
                params, opt = tree["params"], tree["opt"]
                start_step = latest
                print(f"resumed from step {latest}", flush=True)
    elif args.save_every or args.resume:
        ap.error("--save-every/--resume need --ckpt-dir")

    def sample_tokens():
        starts = rng.integers(0, corpus.size - args.seq_len - 1, args.batch)
        return np.stack(
            [corpus[s : s + args.seq_len] for s in starts]
        ).astype(np.int32)

    if spl > 1 and (args.steps - start_step) % spl:
        ap.error(
            f"resumed at step {start_step}: the remaining "
            f"{args.steps - start_step} steps must divide by "
            f"--steps-per-launch {spl}"
        )

    # donate params + opt state: this loop always rebinds both, and the
    # aliasing halves the model-state HBM footprint (params + Adam
    # moments are the dominant buffers at scale). One optimizer step:
    def one(p, opt, *data):
        if zig:
            loss, g = jax.value_and_grad(lm_loss_with_targets)(
                p, *data, cfg, mesh, "data"
            )
        else:
            loss, g = jax.value_and_grad(lm_loss)(p, *data, cfg, mesh, "data")
        up, opt = tx.update(g, opt, p)
        return optax.apply_updates(p, up), opt, loss

    if spl == 1:
        step = jax.jit(one, donate_argnums=(0, 1))
    else:
        # launch = spl sequential steps in one program (scan carries
        # params+opt; each data array gains a leading [spl] dim) —
        # identical trajectory, spl-1 fewer dispatch round trips
        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def step(p, opt, *stacks):
            def body(carry, xs):
                p2, opt2, loss = one(*carry, *xs)
                return (p2, opt2), loss
            (p, opt), losses = jax.lax.scan(body, (p, opt), stacks)
            return p, opt, losses[-1]

    def launch_data():
        """Sharded device arrays for one launch ([spl, ...] when fused)."""
        batches = [sample_tokens() for _ in range(spl)]
        if zig:
            arrs = [zigzag_lm_arrays(t, n_data) for t in batches]
            grouped = list(zip(*arrs))  # (toks), (tgts), (wts)
        else:
            grouped = [batches]
        return tuple(
            shard_tokens(g[0] if spl == 1 else np.stack(g), mesh)
            for g in grouped
        )

    eval_fn = None
    if args.eval_every:
        # fixed held-out batches (never trained on), scored with the
        # same loss the training step uses — zigzag included
        erng = np.random.default_rng(args.seed + 7)
        raw_eval = []
        for _ in range(4):
            starts = erng.integers(
                0, eval_corpus.size - args.seq_len - 1, args.batch
            )
            raw_eval.append(
                np.stack(
                    [eval_corpus[s : s + args.seq_len] for s in starts]
                ).astype(np.int32)
            )
        if zig:
            ev_jit = jax.jit(
                lambda p, t, g, w: lm_loss_with_targets(
                    p, t, g, w, cfg, mesh, "data"
                )
            )
            fixed_eval = [
                tuple(
                    shard_tokens(a, mesh)
                    for a in zigzag_lm_arrays(t, n_data)
                )
                for t in raw_eval
            ]
            eval_fn = lambda p: float(  # noqa: E731
                np.mean([float(ev_jit(p, *tpl)) for tpl in fixed_eval])
            )
        else:
            ev_jit = jax.jit(lambda p, t: lm_loss(p, t, cfg, mesh, "data"))
            fixed_eval = [shard_tokens(t, mesh) for t in raw_eval]
            eval_fn = lambda p: float(  # noqa: E731
                np.mean([float(ev_jit(p, t)) for t in fixed_eval])
            )

    print(f"devices={n_dev} (data={n_data} x server={args.num_servers}) "
          f"attention={cfg.attention} corpus={corpus.size} bytes"
          + (f" (+{eval_corpus.size} held out)" if eval_corpus is not None
             else ""))
    print(f"{'step':>5} {'loss':>9} {'bits/byte':>10}")
    import json as _json
    import time as _time

    from ...utils.profiling import device_trace

    log_f = open(args.log_file, "a") if args.log_file else None
    t_start = _time.perf_counter()
    last_t, last_i = t_start, start_step
    loop_raised = False
    try:
        with device_trace(args.profile):
            for i in range(start_step + spl, args.steps + 1, spl):
                params, opt, loss = step(params, opt, *launch_data())
                report = i % args.report_every < spl or i == args.steps
                ev = None
                rec = None
                if report:
                    ll = float(loss)
                    print(f"{i:>5} {ll:>9.4f} {ll / np.log(2):>10.4f}",
                          flush=True)
                    # throughput window closes BEFORE any eval below so
                    # held-out evaluation never pollutes tokens_per_sec
                    now = _time.perf_counter()
                    rec = {
                        "step": i,
                        "wall_s": round(now - t_start, 2),
                        "loss": round(ll, 6),
                        "bits_per_byte": round(ll / float(np.log(2)), 6),
                        "tokens_per_sec": round(
                            (i - last_i) * args.batch * args.seq_len
                            / max(now - last_t, 1e-9),
                            1,
                        ),
                    }
                    last_t, last_i = now, i
                if eval_fn is not None and (
                    i % args.eval_every < spl or i == args.steps
                ):
                    ev_t0 = _time.perf_counter()
                    ev = eval_fn(params)
                    # shift the open window past the eval's wall time
                    last_t += _time.perf_counter() - ev_t0
                    print(
                        f" eval@{i:<4} {ev:>8.4f} {ev / np.log(2):>10.4f}",
                        flush=True,
                    )
                # telemetry: a line per report interval, PLUS a line for
                # any eval measured off the report grid (an eval curve
                # point must never be silently dropped from the log)
                if log_f is not None and (rec is not None or ev is not None):
                    if rec is None:
                        rec = {
                            "step": i,
                            "wall_s": round(
                                _time.perf_counter() - t_start, 2
                            ),
                        }
                    if ev is not None:
                        rec["eval_loss"] = round(float(ev), 6)
                    log_f.write(_json.dumps(rec) + "\n")
                    log_f.flush()
                if mgr is not None and (
                    i == args.steps
                    or (args.save_every and i % args.save_every == 0)
                ):
                    # --ckpt-dir always saves the final step, so a later
                    # --resume has something to find even without
                    # --save-every. Async: the host snapshot is copied
                    # here (donation-safe), the disk write overlaps the
                    # next training steps.
                    mgr.save_async(i, {"params": params, "opt": opt})
    except BaseException:
        # an explicit flag, NOT sys.exc_info(): inside the drain's
        # except handler below exc_info reports the exception BEING
        # HANDLED (always true there), and even read at the top of the
        # finally it reports handled exceptions from CALLER frames —
        # both readings swallowed a save failure on a clean run (exit
        # 0 with the final checkpoint missing)
        loop_raised = True
        raise
    finally:
        if log_f is not None:
            log_f.close()
        if mgr is not None:
            # drain even when the loop raises: the daemon writer thread
            # would otherwise be killed at interpreter exit (the atomic
            # rename in _write means a kill can only ever leave a .tmp
            # dir, but a completed save beats a discarded one)
            try:
                mgr.wait()
            except RuntimeError as e:
                # an async-save failure is the primary error only when
                # the loop exited cleanly — never mask the loop's own
                # exception (or a Ctrl-C) with the drain's
                if not loop_raised:
                    raise
                print(f"async checkpoint failure during shutdown: {e}",
                      file=sys.stderr)

    if args.prompt is not None:
        prompt = np.frombuffer(
            args.prompt.encode("utf-8", "replace") or b"\n", np.uint8
        ).astype(np.int32)[None, :]
        if args.beam:
            from ...models.transformer import lm_beam_search

            beams, scores = lm_beam_search(
                params, prompt, cfg, steps=args.gen_tokens,
                beam_width=args.beam, eos_id=args.eos_byte,
            )
            out = np.asarray(beams)[0, 0]
            note = f"beam {args.beam}, logprob {float(scores[0, 0]):.2f}"
        else:
            out = np.asarray(
                lm_generate(
                    params, prompt, cfg, steps=args.gen_tokens,
                    temperature=args.temperature, top_k=args.top_k,
                    top_p=args.top_p, eos_id=args.eos_byte,
                    key=jax.random.PRNGKey(args.seed + 1),
                )
            )[0]
            note = "greedy" if not args.temperature else "sampled"
        if args.eos_byte is not None:
            # "eos then pads": truncate at the first stop byte inside
            # the GENERATED region so the terminal never sees the pads
            gen_start = prompt.shape[1]
            hits = np.flatnonzero(out[gen_start:] == args.eos_byte)
            if hits.size:
                out = out[: gen_start + hits[0] + 1]
        text = bytes(out.astype(np.uint8)).decode("utf-8", "replace")
        print(f"--- generation ({args.gen_tokens} tokens, {note}) ---")
        print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
