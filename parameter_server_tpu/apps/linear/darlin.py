"""Darlin: delayed block proximal gradient for L1 logistic regression.

Counterpart of ``src/app/linear_method/darlin.h`` (the reference's batch
solver). Semantics preserved exactly:

- multiplicative dual ``dual_i = exp(y_i · Xw_i)``, ``τ_i = 1/(1+dual_i)``;
- per-block first-order gradient ``G_j = Σ_i −y_i τ_i x_ij`` and
  second-order upper bound
  ``U_j = Σ_i min(τ(1−τ)·e^{|x_ij|·δ_j}, ¼)·x_ij²`` (binary features use
  ``e^{δ_j}``), ref ComputeGradient (darlin.h:417-462);
- server shrink step with trust region ``δ`` and KKT filter / active set,
  ref UpdateWeight (darlin.h:261-306): suspended coordinates are skipped
  until ``reset_kkt_filter``;
- ``Δ(δmax, d) = min(δmax, 2|d| + 0.1)`` (darlin.h:174);
- dual update ``dual_i *= exp(y_i · x_ij · d_j)``, ref UpdateDual;
- scheduler loop with randomized block order, bounded block delay τ, KKT
  threshold annealing ``thr = violation/num_ex · ratio`` and the
  reset-on-converge double-check, ref DarlinScheduler::Run.

TPU mapping: examples are sharded over the data axis (dual lives sharded);
block weights/δ/active-set are replicated (blocks are small); per-block
G/U are segment-sums over static-shape COO column blocks followed by a
psum over the data axis — that psum IS the worker→server gradient push of
the reference, and the broadcasted shrink result IS the server→worker
weight pull.

Bounded delay τ (ref darlin.h AddWaitTime / Submit with wait ≤ τ): block
steps are submitted through the Executor with a dependency on step
``ts − τ − 1``, so up to τ+1 block updates are in flight. All block state
(w/δ/active per block, the dual) stays device-resident; the host never
blocks on a step's result inside a pass, it only waits for the bounded-
delay horizon — XLA's async dispatch pipelines the queued steps while the
host prepares the next submissions, reproducing the reference's overlap
of block compute with communication.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...utils import file as psfile

from ...utils.compat import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from ...learner.bcd import BCDProgress, BCDScheduler, FeatureBlock
from ...parallel import mesh as meshlib
from ...parallel.mesh import DATA_AXIS
from ...utils.sparse import SparseBatch
from .config import BCDConfig, Config


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class ColBlock:
    """Static-shape CSC column block, example rows sharded over data axis."""

    rows: np.ndarray  # [D, NZ] int32 — local example ids (rows_pad sentinel)
    cols: np.ndarray  # [D, NZ] int32 — block-local column ids
    vals: np.ndarray  # [D, NZ] float32 (0 ⇒ padding)
    num_cols: int = dataclasses.field(metadata={"static": True})


def _pow2_bucket(n: int, floor: int = 1024) -> int:
    b = floor
    while b < n:
        b <<= 1
    return b


class DarlinSolver:
    """Fused worker+server for one darlin run (ref DarlinWorker+DarlinServer)."""

    def __init__(self, conf: Config, mesh=None):
        from ...system.postoffice import Postoffice

        self.conf = conf
        self.bcd: BCDConfig = conf.darlin or BCDConfig()
        self.mesh = mesh if mesh is not None else Postoffice.instance().mesh
        assert self.mesh is not None, "Postoffice.start() first"
        self.lam = float(conf.penalty.lambda_[0])
        self.eta = float(conf.learning_rate.alpha)
        self.n_workers = meshlib.num_workers(self.mesh)
        self._block_steps: Dict[Tuple[int, int], object] = {}
        # device state, set by init_data
        self.y: Optional[jax.Array] = None
        self.dual: Optional[jax.Array] = None
        self.row_mask: Optional[jax.Array] = None
        # per-block device-resident model state (jax arrays) — the host
        # never syncs on these inside a pass (τ-delay pipelining)
        self.w_blk: List[jax.Array] = []
        self.delta_blk: List[jax.Array] = []
        self.active_blk: List[jax.Array] = []
        self.fea_blocks: List[FeatureBlock] = []
        self.blocks: List[ColBlock] = []
        self.num_ex = 0
        self.num_cols = 0
        self.rows_per_shard = 0

    # -- preprocessing (ref BCDWorker::PreprocessData) --

    def init_data(self, data: SparseBatch, fea_blocks: List[FeatureBlock]) -> None:
        n = data.n
        d = self.n_workers
        per = -(-n // d)
        self.rows_per_shard = per
        self.num_ex = n
        y = np.zeros((d, per), np.float32)
        mask = np.zeros((d, per), np.float32)
        for s in range(d):
            lo, hi = min(s * per, n), min((s + 1) * per, n)
            y[s, : hi - lo] = data.y[lo:hi]
            mask[s, : hi - lo] = 1.0
        batch_sh = NamedSharding(self.mesh, P(DATA_AXIS))
        self.y = jax.device_put(jnp.asarray(y), batch_sh)
        self.row_mask = jax.device_put(jnp.asarray(mask), batch_sh)
        self.dual = jax.device_put(jnp.ones((d, per), jnp.float32), batch_sh)

        f = data.cols
        self.num_cols = f
        self.fea_blocks = list(fea_blocks)
        self.w_blk, self.delta_blk, self.active_blk = [], [], []
        for blk in fea_blocks:
            c = blk.col_range.size()
            self.w_blk.append(jnp.zeros(c, jnp.float32))
            self.delta_blk.append(
                jnp.full(c, self.bcd.delta_init_value, jnp.float32)
            )
            self.active_blk.append(jnp.ones(c, bool))

        # build per-block static COO (cols local to block, rows local to shard)
        csc = data.to_csc()
        rows_global = csc.row_ids
        vals_global = csc.values
        self.blocks = []
        for blk in fea_blocks:
            c0, c1 = blk.col_range.begin, blk.col_range.end
            lo, hi = csc.colptr[c0], csc.colptr[c1]
            cols_rep = np.repeat(
                np.arange(c1 - c0, dtype=np.int32),
                np.diff(csc.colptr[c0 : c1 + 1]).astype(np.int64),
            )
            rows_blk = rows_global[lo:hi]
            vals_blk = (
                np.ones(hi - lo, np.float32) if vals_global is None else vals_global[lo:hi]
            )
            # split by example shard
            shard_ids = np.minimum(rows_blk // per, d - 1)
            nz_pad = _pow2_bucket(int(np.bincount(shard_ids, minlength=d).max()) if hi > lo else 1)
            rows_arr = np.zeros((d, nz_pad), np.int32)
            cols_arr = np.zeros((d, nz_pad), np.int32)
            vals_arr = np.zeros((d, nz_pad), np.float32)
            for s in range(d):
                sel = shard_ids == s
                k = int(sel.sum())
                rows_arr[s, :k] = rows_blk[sel] - s * per
                cols_arr[s, :k] = cols_rep[sel]
                vals_arr[s, :k] = vals_blk[sel]
            self.blocks.append(
                ColBlock(rows=rows_arr, cols=cols_arr, vals=vals_arr, num_cols=c1 - c0)
            )

    # -- the fused per-block device step --

    def _get_step(self, num_cols: int, nz_pad: int):
        key = (num_cols, nz_pad)
        if key in self._block_steps:
            return self._block_steps[key]
        lam, eta = self.lam, self.eta
        delta_max = self.bcd.delta_max_value
        rows_per = self.rows_per_shard

        def local(w, delta, active, dual, y, mask, rows, cols, vals, thr, reset):
            y, mask, dual = y[0], mask[0], dual[0]
            rows, cols, vals = rows[0], cols[0], vals[0]
            active = jnp.where(reset > 0, jnp.ones_like(active), active)

            tau = 1.0 / (1.0 + dual)  # [R]
            tr = tau[rows]
            yr = y[rows]
            # G_j and U_j (ref ComputeGradient): padding vals=0 contribute 0
            g_col = jax.ops.segment_sum(-yr * tr * vals, cols, num_segments=num_cols)
            d_col = delta  # [C] block-local
            curv = jnp.minimum(
                tr * (1 - tr) * jnp.exp(jnp.abs(vals) * d_col[cols]), 0.25
            )
            u_col = jax.ops.segment_sum(curv * vals * vals, cols, num_segments=num_cols)
            g_col = jax.lax.psum(g_col, DATA_AXIS)  # the gradient push
            u_col = jax.lax.psum(u_col, DATA_AXIS)

            # server shrink update (ref UpdateWeight)
            u = u_col / eta + 1e-10
            g_pos = g_col + lam
            g_neg = g_col - lam
            w_zero = w == 0
            vio = jnp.where(
                w_zero & active,
                jnp.where(g_pos < 0, -g_pos, jnp.where(g_neg > 0, g_neg, 0.0)),
                0.0,
            )
            violation = jnp.max(vio)
            deactivate = w_zero & active & (g_pos > thr) & (g_neg < -thr) & (vio == 0)
            new_active = active & ~deactivate

            d_w = jnp.where(
                g_pos <= u * w, -g_pos / u, jnp.where(g_neg >= u * w, -g_neg / u, -w)
            )
            d_w = jnp.clip(d_w, -delta, delta)
            d_w = jnp.where(new_active, d_w, 0.0)
            new_delta = jnp.where(
                new_active, jnp.minimum(delta_max, 2.0 * jnp.abs(d_w) + 0.1), delta
            )
            new_w = w + d_w

            # dual update (ref UpdateDual): dual *= exp(y * x * d_w)
            xdw = jax.ops.segment_sum(vals * d_w[cols], rows, num_segments=rows_per)
            new_dual = dual * jnp.exp(y * xdw) * mask + (1 - mask)

            return new_w, new_delta, new_active, new_dual[None, :], violation

        batch_spec = P(DATA_AXIS)

        @jax.jit
        def step(w, delta, active, dual, y, mask, rows, cols, vals, thr, reset):
            return shard_map(
                local,
                mesh=self.mesh,
                in_specs=(
                    P(), P(), P(),
                    batch_spec, batch_spec, batch_spec,
                    batch_spec, batch_spec, batch_spec,
                    P(), P(),
                ),
                out_specs=(P(), P(), P(), batch_spec, P()),
                check_vma=False,
            )(w, delta, active, dual, y, mask, rows, cols, vals, thr, reset)

        self._block_steps[key] = step
        return step

    def dispatch_block(self, blk_id: int, thr: float, reset: bool) -> jax.Array:
        """Dispatch one block update WITHOUT host sync; returns the block's
        KKT violation as an async device scalar (ref Submit(UPDATE_MODEL)).

        The new block state replaces the device references immediately —
        XLA's dependency tracking chains consecutive steps through the
        shared dual, so program order is preserved while the host runs
        ahead (bounded by the scheduler's τ horizon)."""
        data = self.blocks[blk_id]
        step = self._get_step(data.num_cols, data.vals.shape[-1])
        new_w, new_delta, new_active, new_dual, violation = step(
            self.w_blk[blk_id],
            self.delta_blk[blk_id],
            self.active_blk[blk_id],
            self.dual,
            self.y,
            self.row_mask,
            data.rows,
            data.cols,
            data.vals,
            jnp.float32(thr),
            jnp.int32(1 if reset else 0),
        )
        self.w_blk[blk_id] = new_w
        self.delta_blk[blk_id] = new_delta
        self.active_blk[blk_id] = new_active
        self.dual = new_dual
        return violation

    def update_block(
        self, blk_id: int, fea_blocks: List[FeatureBlock], thr: float, reset: bool
    ) -> float:
        """Synchronous single-block update (parity tests / debugging)."""
        del fea_blocks  # block geometry is fixed at init_data
        return float(self.dispatch_block(blk_id, thr, reset))

    def reset_active(self) -> None:
        """Re-activate every coordinate (ref reset_kkt_filter → fill(true))."""
        self.active_blk = [jnp.ones_like(a) for a in self.active_blk]

    # -- host views of the device-resident model (materialize on demand) --

    def _assemble(self, parts: List[jax.Array], fill, dtype) -> np.ndarray:
        out = np.full(self.num_cols, fill, dtype)
        for blk, p in zip(self.fea_blocks, parts):
            out[blk.col_range.begin : blk.col_range.end] = np.asarray(p)
        return out

    @property
    def w(self) -> np.ndarray:
        return self._assemble(self.w_blk, 0.0, np.float32)

    @property
    def delta(self) -> np.ndarray:
        return self._assemble(self.delta_blk, self.bcd.delta_init_value, np.float32)

    @property
    def active(self) -> np.ndarray:
        return self._assemble(self.active_blk, True, bool)

    # -- evaluation (ref DarlinServer::Evaluate + worker objective) --

    def evaluate(self) -> BCDProgress:
        # objective = sum log(1+exp(-y Xw)) + λ|w|_1; dual = exp(y Xw)
        dual = np.asarray(self.dual)
        mask = np.asarray(self.row_mask) > 0
        logloss = float(np.log1p(1.0 / dual[mask]).sum())
        w = self.w  # materialize the device blocks once
        return BCDProgress(
            objective=logloss + self.lam * float(np.abs(w).sum()),
            nnz_w=int((w != 0).sum()),
            nnz_active_set=int(self.active.sum()),
        )

    def predict_margin(self) -> np.ndarray:
        """Xw for the training examples, from the dual (exp(y·Xw))."""
        dual = np.asarray(self.dual)
        y = np.asarray(self.y)
        mask = np.asarray(self.row_mask) > 0
        return (np.log(dual[mask]) / np.where(y[mask] != 0, y[mask], 1.0)).ravel()


class DarlinScheduler(BCDScheduler):
    """ref DarlinScheduler::Run — the full training loop."""

    def __init__(self, conf: Config, mesh=None, name: str = "darlin_scheduler"):
        super().__init__(conf.darlin or BCDConfig(), name=name)
        self.conf = conf
        # comm_filter parity (ref bcd.conf): KEY_CACHING is structurally
        # subsumed — feature blocks stay device-resident across passes, so
        # keys are never resent at all; other filter types would change
        # numerics and warn rather than silently no-op
        import logging

        for f in (conf.darlin.comm_filter if conf.darlin else []) or []:
            ftype = str(f.get("type", "") if isinstance(f, dict) else f).lower()
            if ftype not in ("key_caching", "compressing"):
                logging.getLogger(__name__).warning(
                    "darlin comm_filter %r is not applied (blocks are "
                    "device-resident; only key_caching/compressing "
                    "semantics are subsumed)", ftype,
                )
        self.solver = DarlinSolver(conf, mesh=mesh)
        self.seed = 0
        self._converged_once = False
        # τ-delay instrumentation. max_dispatch_window counts steps the host
        # submitted without waiting for completion (the bounded-delay window
        # the scheduler is ALLOWED to run ahead — deterministic, = τ+1 when
        # enough blocks exist). max_in_flight_observed probes jax.Array
        # .is_ready() at submit time: steps whose device computation had
        # genuinely not finished yet (timing-dependent; reported, the window
        # is what tests assert on).
        self.max_dispatch_window = 0
        self.max_in_flight_observed = 0

    def run_on(self, data: SparseBatch, verbose: bool = False) -> BCDProgress:
        self.set_data(data)
        return self.run_loaded(verbose=verbose)

    def run_loaded(self, verbose: bool = False) -> BCDProgress:
        """Train on already-loaded/localized data (after load_data)."""
        assert self.conf.loss.type == "logit", "darlin trains l1-logit"
        assert self.conf.penalty.type == "l1"
        assert self.data is not None, "load data first"
        localized = self.data
        blocks = self.divide_feature_blocks()
        self.solver.init_data(localized, blocks)

        from ...system.executor import Executor

        # bounded block delay τ (ref darlin.h AddWaitTime: step ts waits on
        # everything up to ts − τ − 1, so ≤ τ+1 block tasks are in flight)
        tau = max(0, self.bcd_conf.max_block_delay)
        executor = Executor(name=self.name)
        rng = random.Random(self.seed)
        try:
            return self._run_passes(executor, tau, rng, verbose)
        finally:
            executor.stop()

    def _run_passes(self, executor, tau, rng, verbose) -> BCDProgress:
        from ...system.message import Task

        kkt_threshold = 1e20
        reset_kkt = False
        prev_objv = None
        prog = BCDProgress()
        for iteration in range(self.bcd_conf.num_data_pass):
            order = list(self.blk_order)
            if self.bcd_conf.random_feature_block_order:
                rng.shuffle(order)
            if reset_kkt:
                # reference resets the active set for ALL groups
                # (darlin.h Update: reset_kkt_filter -> fill(true) per grp)
                self.solver.reset_active()
                reset_kkt = False
            pass_start = executor.time()
            pending_ts = []
            for blk_id in order:
                dep = executor.time() - (tau + 1)
                task = Task(wait_time=[dep] if dep >= pass_start else [])
                ts = executor.submit(
                    lambda b=blk_id, t=kkt_threshold: self.solver.dispatch_block(
                        b, t, reset=False
                    ),
                    task,
                )
                pending_ts.append(ts)
                # probe genuine device-side concurrency: dispatched steps
                # whose violation scalars have not materialized yet
                probe = 0
                for t in pending_ts:
                    v = executor.result(t)
                    if v is not None and hasattr(v, "is_ready") and not v.is_ready():
                        probe += 1
                self.max_in_flight_observed = max(
                    self.max_in_flight_observed, probe
                )
            self.po.beat(self.name)  # liveness signal (ref heartbeat thread)
            vios = [executor.wait(t) for t in pending_ts]
            self.max_dispatch_window = max(
                self.max_dispatch_window, executor.max_dispatched_in_flight
            )
            violation = max(
                (float(v) for v in vios if v is not None), default=0.0
            )
            prog = self.solver.evaluate()
            prog.violation = violation
            if prev_objv is not None and prev_objv > 0:
                prog.relative_obj = (prev_objv - prog.objective) / prev_objv
            self.merge_progress(iteration, prog)
            if verbose:
                print(self.show_progress(iteration))
            # KKT threshold annealing (ref Run: vio/num_ex*ratio)
            kkt_threshold = (
                violation / max(1, self.solver.num_ex)
                * self.bcd_conf.kkt_filter_threshold_ratio
            )
            rel = prog.relative_obj
            if prev_objv is not None and 0 <= rel <= self.bcd_conf.epsilon:
                if reset_kkt is False and self._converged_once:
                    break
                self._converged_once = True
                reset_kkt = True  # double-check with full active set
            else:
                self._converged_once = False
            prev_objv = prog.objective
        return prog

    def save_model(self, path: str) -> List[str]:
        """key\\tweight text dump, one file per server shard named
        ``{path}_S{k}`` (ref BCDServer::SaveModel → WriteToFile with
        ``file + "_" + MyNodeID()``; eval configs match ``model_S.*``).
        Shards take contiguous key ranges (Range::EvenDivide)."""
        keys = self.global_keys
        w = self.solver.w
        n_server = meshlib.num_servers(self.solver.mesh)
        bounds = [len(keys) * s // n_server for s in range(n_server + 1)]
        written = []
        for s in range(n_server):
            spath = f"{path}_S{s}"
            with psfile.open_write(spath) as f:
                for i in range(bounds[s], bounds[s + 1]):
                    v = w[i]
                    if v != 0 and not np.isnan(v):
                        f.write(f"{keys[i]}\t{float(v)!r}\n")
            written.append(spath)
        return written
